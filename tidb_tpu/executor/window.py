"""WindowExec (ref: executor/window.go — the window-function executor).

Runs as a root-task operator over materialized rows, like Sort (the
reference likewise evaluates windows on the SQL node, not in
coprocessors). One pass: lexsort by (partition keys, order keys),
compute the function over partition segments with numpy, scatter the
values back to the original row order, and re-emit the child's chunks
with the output column attached.

Frame semantics (MySQL defaults):
  * no ORDER BY  -> the whole partition is the frame
  * with ORDER BY -> RANGE UNBOUNDED PRECEDING .. CURRENT ROW: peers
    (rows tying on the order keys) share the frame result
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from tidb_tpu.chunk.chunk import Chunk
from tidb_tpu.chunk.column import Column
from tidb_tpu.errors import UnsupportedError
from tidb_tpu.executor.base import ExecContext, Executor
from tidb_tpu.executor.sort import _Materializing, _sort_order
from tidb_tpu.types import TypeKind

__all__ = ["WindowExec"]


class WindowExec(_Materializing):
    def __init__(self, schema, child, func: str, args, partition_by,
                 order_by, out_uid: str, out_type, params: tuple = (),
                 frame=None):
        super().__init__(schema, [child])
        self.func = func
        self.args = args
        self.partition_by = partition_by
        self.order_by = order_by
        self.out_uid = out_uid
        self.out_type = out_type
        self.params = params
        self.frame = frame  # ("rows", lo_bound, hi_bound) or None

    def open(self, ctx: ExecContext) -> None:
        Executor.open(self, ctx)
        self.ctx = ctx
        # drain with (partition keys, order keys, arg) evaluated per chunk
        key_items = ([(e, False) for e in self.partition_by]
                     + list(self.order_by)
                     + [(a, False) for a in self.args])
        child_schema = self.schema[:-1]  # the out column isn't in the child
        saved = self.schema
        self.schema = child_schema
        try:
            runs = self._drain_to_runs(key_items)
            host_keys = self._global_keys(runs, len(key_items))
            n = len(host_keys[0][0]) if key_items else sum(
                r for _, r in runs.all_runs())
            np_part = len(self.partition_by)
            np_ord = len(self.order_by)
            descale = 1.0
            if (self.func == "avg" and self.args
                    and self.args[0].type_.kind == TypeKind.DECIMAL):
                descale = float(10 ** self.args[0].type_.scale)
            vals, valid = _compute_window(
                self.func, host_keys[:np_part],
                host_keys[np_part : np_part + np_ord],
                list(self.order_by),
                host_keys[np_part + np_ord :],
                n, self.out_type, avg_descale=descale,
                params=self.params, frame=self.frame)
            self._emit(runs, None, n)  # original row order
        finally:
            self.schema = saved
            self._close_runs()
        # attach the output column, sliced chunk-by-chunk
        cap = self.ctx.chunk_capacity
        out_col = self.schema[-1]
        patched = []
        off = 0
        for ch in self._chunks:
            m = int(np.asarray(ch.sel).sum())
            d = np.zeros(cap, dtype=out_col.type_.np_dtype)
            v = np.zeros(cap, dtype=np.bool_)
            d[:m] = vals[off : off + m]
            v[:m] = valid[off : off + m]
            cols = dict(ch.columns)
            cols[self.out_uid] = Column(d, v, out_col.type_)
            patched.append(Chunk(cols, ch.sel))
            off += m
        self._chunks = patched


def _frame_edges(frame, idx, part_start, part_end,
                 tie_start=None, tie_last=None):
    """Per-row inclusive [s, e] sorted-index window for an explicit
    frame; empty windows surface as s > e. ROWS counts physical rows
    from the current row; RANGE's CURRENT ROW means the current PEER
    GROUP (tie_start/tie_last), per the standard."""
    kind, lo, hi = frame

    def edge(bound, is_lo):
        if bound[0] == "unbounded_preceding":
            return part_start.copy()
        if bound[0] == "unbounded_following":
            return part_end.copy()
        if bound[0] == "current":
            if kind == "range":
                return (tie_start if is_lo else tie_last).copy()
            return idx.copy()
        off = bound[1]
        return idx + (-off if bound[0] == "preceding" else off)

    s = np.maximum(edge(lo, True), part_start)
    e = np.minimum(edge(hi, False), part_end)
    return s, e


def _compute_window(func, part_keys, order_keys, order_items, arg_keys,
                    n: int, out_type, avg_descale: float = 1.0,
                    params: tuple = (), frame=None):
    """Returns (values[n], valid[n]) in ORIGINAL row order."""
    if n == 0:
        return (np.zeros(0, dtype=out_type.np_dtype),
                np.zeros(0, dtype=np.bool_))
    # global order: partitions ascending, then the window's ORDER BY
    items = [(None, False)] * len(part_keys) + [(None, d) for _, d in order_items]
    perm = _sort_order(part_keys + order_keys, items) if items else np.arange(n)

    def g(keys):  # gather (data, valid) pairs into sorted order
        return [(d[perm], v[perm]) for d, v in keys]

    sp, so = g(part_keys), g(order_keys)

    def _neq(d, v):
        """sorted-adjacent inequality; NULLs equal each other."""
        both_valid = v[1:] & v[:-1]
        both_null = ~v[1:] & ~v[:-1]
        return ~((both_valid & (d[1:] == d[:-1])) | both_null)

    # partition starts in sorted order
    new_part = np.zeros(n, dtype=np.bool_)
    new_part[0] = True
    for d, v in sp:
        new_part[1:] |= _neq(d, v)
    pid = np.cumsum(new_part) - 1  # partition id per sorted row
    starts = np.nonzero(new_part)[0]
    part_start = starts[pid]  # first sorted index of each row's partition

    # tie groups (same partition + same ORDER BY keys)
    new_tie = new_part.copy()
    for d, v in so:
        new_tie[1:] |= _neq(d, v)
    tid = np.cumsum(new_tie) - 1
    tstarts = np.nonzero(new_tie)[0]
    tie_start = tstarts[tid]
    # last sorted index of each tie group
    tlast = np.empty(len(tstarts), dtype=np.int64)
    tlast[:-1] = tstarts[1:] - 1
    tlast[-1] = n - 1
    tie_last = tlast[tid]

    idx = np.arange(n)
    out_valid = np.ones(n, dtype=np.bool_)

    # partition last index (for LEAD bounds / unordered LAST_VALUE)
    pends = np.empty(len(starts), dtype=np.int64)
    pends[:-1] = starts[1:] - 1
    pends[-1] = n - 1
    part_end = pends[pid]

    if func in ("lead", "lag", "first_value", "last_value", "ntile"):
        if func == "ntile":
            nb = int(params[0])
            size = part_end - part_start + 1
            k = idx - part_start
            base = size // nb
            rem = size % nb
            thresh = rem * (base + 1)
            with np.errstate(divide="ignore", invalid="ignore"):
                svals = np.where(
                    k < thresh,
                    k // np.maximum(base + 1, 1) + 1,
                    rem + (k - thresh) // np.maximum(base, 1) + 1)
        else:
            ad, av = arg_keys[0][0][perm], arg_keys[0][1][perm]
            if func == "first_value":
                if frame is not None:
                    fs, fe = _frame_edges(frame, idx, part_start, part_end,
                                          tie_start, tie_last)
                    src_i = np.clip(fs, 0, n - 1)
                    inwin = fs <= fe
                else:
                    src_i = part_start
                    inwin = np.ones(n, dtype=np.bool_)
            elif func == "last_value":
                if frame is not None:
                    fs, fe = _frame_edges(frame, idx, part_start, part_end,
                                          tie_start, tie_last)
                    src_i = np.clip(fe, 0, n - 1)
                    inwin = fs <= fe
                else:
                    # default frame: up to the current tie group
                    # (ordered), whole partition otherwise
                    src_i = tie_last if order_items else part_end
                    inwin = np.ones(n, dtype=np.bool_)
            else:
                off = int(params[0])
                src_i = idx - off if func == "lag" else idx + off
                inwin = (src_i >= part_start) & (src_i <= part_end)
                src_i = np.clip(src_i, 0, n - 1)
            svals = ad[src_i]
            out_valid = av[src_i] & inwin
            if func in ("lead", "lag") and len(params) > 1:
                _off, dval, dnull = params
                if not dnull:
                    dv = out_type.np_dtype.type(dval)
                    svals = np.where(inwin, svals, dv)
                    out_valid = np.where(inwin, out_valid, True)
    elif func == "row_number":
        svals = idx - part_start + 1
    elif func == "rank":
        svals = tie_start - part_start + 1
    elif func == "dense_rank":
        # tie index within the partition
        svals = tid - tid[part_start] + 1
    else:
        has_arg = bool(arg_keys)
        if has_arg:
            ad, av = arg_keys[0][0][perm], arg_keys[0][1][perm]
        else:  # COUNT(*)
            ad = np.ones(n, dtype=np.int64)
            av = np.ones(n, dtype=np.bool_)
        framed = bool(order_items)  # running frame vs whole partition
        if func in ("count", "sum", "avg"):
            fd = ad.astype(np.float64) if func == "avg" else ad.astype(
                np.int64 if not np.issubdtype(ad.dtype, np.floating) else np.float64)
            ones = av.astype(np.int64)
            contrib = np.where(av, fd, 0)
            if frame is not None:
                # explicit ROWS frame: windowed prefix-sum differences;
                # no peer sharing (ROWS counts physical rows)
                fs, fe = _frame_edges(frame, idx, part_start, part_end,
                                          tie_start, tie_last)
                cs = np.concatenate(([0], np.cumsum(contrib)))
                cn = np.concatenate(([0], np.cumsum(ones)))
                lo = np.clip(fs, 0, n)
                hi = np.clip(fe + 1, 0, n)
                nonempty = fs <= fe
                run_s = np.where(nonempty, cs[hi] - cs[lo], 0)
                run_n = np.where(nonempty, cn[hi] - cn[lo], 0)
            elif framed:
                cs = np.cumsum(contrib)
                cn = np.cumsum(ones)
                base_s = cs[part_start] - contrib[part_start]
                base_n = cn[part_start] - ones[part_start]
                run_s = cs - base_s
                run_n = cn - base_n
                # RANGE frame: peers share the tie group's last value
                run_s = run_s[tie_last]
                run_n = run_n[tie_last]
            else:
                tot_s = np.add.reduceat(contrib, starts)
                tot_n = np.add.reduceat(ones, starts)
                run_s = tot_s[pid]
                run_n = tot_n[pid]
            if func == "count":
                svals = run_n
            elif func == "sum":
                svals = run_s
                out_valid = run_n > 0  # SUM of no rows is NULL
            else:
                with np.errstate(divide="ignore", invalid="ignore"):
                    svals = np.where(run_n > 0,
                                     run_s / np.maximum(run_n, 1) / avg_descale,
                                     0.0)
                out_valid = run_n > 0
        elif func in ("min", "max"):
            red = np.minimum if func == "min" else np.maximum
            big = (np.inf if np.issubdtype(ad.dtype, np.floating)
                   else np.iinfo(np.int64).max)
            ident = big if func == "min" else -big
            cd = np.where(av, ad, ident)
            ones = av.astype(np.int64)
            if frame is not None:
                fs, fe = _frame_edges(frame, idx, part_start, part_end,
                                          tie_start, tie_last)
                cn = np.concatenate(([0], np.cumsum(ones)))
                lo = np.clip(fs, 0, n)
                hi = np.clip(fe + 1, 0, n)
                nonempty = fs <= fe
                run_n = np.where(nonempty, cn[hi] - cn[lo], 0)
                # per-partition sliding extremes (O(P) loop like the
                # running path; sliding_window_view when both bounds are
                # finite, prefix/suffix accumulates otherwise)
                run = np.full(n, ident, dtype=cd.dtype)
                _k, flo, fhi = frame

                def _off(b):
                    if b[0] == "current":
                        return 0
                    return -b[1] if b[0] == "preceding" else b[1]

                for s0, e0 in zip(starts, list(starts[1:]) + [n]):
                    seg = cd[s0:e0]
                    m = e0 - s0
                    if flo[0] == "unbounded_preceding":
                        # prefix extreme at the (clipped) frame end
                        pref = red.accumulate(seg)
                        eseg = np.clip(fe[s0:e0] - s0, 0, m - 1)
                        run[s0:e0] = pref[eseg]
                    elif fhi[0] == "unbounded_following":
                        suf = red.accumulate(seg[::-1])[::-1]
                        sseg = np.clip(fs[s0:e0] - s0, 0, m - 1)
                        run[s0:e0] = suf[sseg]
                    elif _k == "range":
                        # CURRENT..CURRENT peer-group extreme (the only
                        # remaining RANGE combo: bounds are tie groups)
                        tl = np.clip(fe[s0:e0] - s0, 0, m - 1)
                        pref = red.accumulate(seg)
                        ts_ = np.clip(fs[s0:e0] - s0, 0, m - 1)
                        suf = red.accumulate(seg[::-1])[::-1]
                        # extreme over [ts, tl]: windows never overlap
                        # across tie groups, so prefix-from-tie-start
                        # works: min(prefix[tl], suffix[ts]) over the
                        # group equals reduceat — use reduceat directly
                        gstart = np.unique(ts_)
                        gmin = red.reduceat(seg, gstart)
                        gmap = np.searchsorted(gstart, ts_)
                        run[s0:e0] = gmin[gmap]
                    else:
                        lo_off, hi_off = _off(flo), _off(fhi)
                        w = hi_off - lo_off + 1
                        if w < 1:
                            continue  # every window empty
                        if w >= m:
                            # windows at least partition-sized: every
                            # clipped window is a prefix or a suffix —
                            # O(m) instead of O(m*w)
                            pref = red.accumulate(seg)
                            suf = red.accumulate(seg[::-1])[::-1]
                            a = np.arange(m) + lo_off   # unclipped start
                            b = np.clip(np.arange(m) + hi_off, 0, m - 1)
                            run[s0:e0] = np.where(
                                a <= 0, pref[b],
                                suf[np.clip(a, 0, m - 1)])
                            continue
                        # both bounds finite, narrow: identity padding
                        # makes edge-clipped windows fall out of one
                        # vectorized sliding extreme
                        pad = np.full(w - 1, ident, dtype=seg.dtype)
                        padded = np.concatenate([pad, seg, pad])
                        sw = np.lib.stride_tricks.sliding_window_view(
                            padded, w)
                        ext = (sw.min(axis=1) if func == "min"
                               else sw.max(axis=1))
                        # seg-coord window start r+lo_off lives at
                        # sliding index r+lo_off+(w-1)
                        widx = np.arange(m) + lo_off + (w - 1)
                        run[s0:e0] = ext[np.clip(widx, 0, len(ext) - 1)]
                run = np.where(nonempty, run, ident)
            elif framed:
                # partition-segmented running min/max (O(P) python loop
                # over partitions; acceptable for a root operator)
                run = np.empty_like(cd)
                for s, e in zip(starts, list(starts[1:]) + [n]):
                    run[s:e] = red.accumulate(cd[s:e])
                cn = np.cumsum(ones)
                run_n = cn - (cn[part_start] - ones[part_start])
                run = run[tie_last]
                run_n = run_n[tie_last]
            else:
                tot = red.reduceat(cd, starts)
                run = tot[pid]
                run_n = np.add.reduceat(ones, starts)[pid]
            svals = run
            out_valid = run_n > 0
        else:
            raise UnsupportedError(f"window function {func}")

    # scatter back to original row order
    inv = np.empty(n, dtype=np.int64)
    inv[perm] = np.arange(n)
    vals_sorted = np.asarray(svals)
    out = vals_sorted[inv].astype(out_type.np_dtype)
    return out, out_valid[inv]
