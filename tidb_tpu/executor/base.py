"""Executor protocol and execution context."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

import numpy as np

from tidb_tpu.chunk.chunk import Chunk
from tidb_tpu.planner.binder import PlanCol

__all__ = ["ExecContext", "Executor", "ResultSet", "RuntimeStats",
           "run_plan", "raise_if_cancelled"]


def raise_if_cancelled(ctx: "ExecContext") -> None:
    """Poll the context's cancel hook (KILL flags + statement deadline).

    The hook may return a bool (legacy callers) or an exception instance
    carrying the cancellation REASON — a deadline expiry must surface as
    the MySQL "maximum statement execution time exceeded" error, not as
    a generic KILL. Every long executor loop (the chunk loop here, the
    streamed fragment loops on the dist tier) polls through this one
    function so the classification can never diverge."""
    if ctx.cancel_check is None:
        return
    r = ctx.cancel_check()
    if not r:
        return
    if isinstance(r, BaseException):
        raise r
    from tidb_tpu.errors import QueryKilledError

    raise QueryKilledError("Query execution was interrupted (KILL)")


@dataclass
class RuntimeStats:
    """Per-operator stats surfaced by EXPLAIN ANALYZE
    (ref: util/execdetails RuntimeStats)."""

    rows: int = 0
    chunks: int = 0
    open_wall: float = 0.0
    next_wall: float = 0.0
    # device round trips (kernel launches + transfers) issued while this
    # operator (incl. its children) ran — utils.dispatch deltas; EXPLAIN
    # ANALYZE shows own = cumulative - children's
    dispatches: int = 0
    # kernel (re)traces while this operator ran (dispatch.compile_count
    # deltas): nonzero on a warm re-execution means a shape key leaked
    # into traced code
    recompiles: int = 0
    # perf_counter of this operator's FIRST open/next activity — async
    # fragment dispatches overlap, and without a start offset EXPLAIN
    # ANALYZE / TRACE render them as if sequential
    first_ts: Optional[float] = None
    # columnar segment store (ISSUE 8): segments this scan skipped via
    # zone-map pruning vs segments it actually staged; zero/zero on
    # operators (or tables) without a segment store
    segs_pruned: int = 0
    segs_scanned: int = 0
    # pipelined execution (ISSUE 9): chunks whose staged device buffers
    # were already in place when the compute loop asked — prefetch hits
    # plus device-buffer-cache hits. EXPLAIN ANALYZE's `staged` column
    staged: int = 0
    # plan feedback (ISSUE 15): the planner's row estimate for the plan
    # node this executor answers for (-1 = unannotated), and the actual
    # output rows the operator learned HOST-SIDE FOR FREE (-1 = never
    # known without instrumentation): joins fill it from their already-
    # batched match-total fetches, aggregates from the group count at
    # finalize — no new per-chunk device syncs. `measured` marks rows as
    # exact (the instrument() wrapper counted every emitted chunk);
    # feedback harvest prefers `rows` then, else `out_rows`.
    est_rows: float = -1.0
    out_rows: int = -1
    measured: bool = False
    # fused scan→probe tile telemetry (feedback consumer: tile-capacity
    # sizing): chunks probed / chunks whose expansion overflowed the
    # in-program tile / the worst ceil(overflow/cap) tile need seen
    tile_chunks: int = 0
    tile_overflows: int = 0
    tile_max_need: int = 0

    def add_out_rows(self, n: int) -> None:
        """Fold a host-known output count into out_rows, owning the
        -1 = unknown sentinel so call sites don't each re-implement
        the set-vs-accumulate split."""
        self.out_rows = n if self.out_rows < 0 else self.out_rows + n


@dataclass
class ExecContext:
    chunk_capacity: int = 1 << 16
    collect_stats: bool = False
    # MVCC snapshot: None reads committed-latest; a txn's reads carry its
    # start ts and marker so it sees its own provisional writes
    read_ts: Optional[int] = None
    txn_marker: int = 0
    # KILL support: polled between chunks; return True to cancel
    cancel_check: Optional[object] = None
    # host-side memory accounting root (budget + spill/OOM actions live
    # here; ref: the per-query memory.Tracker in sessionctx)
    mem_tracker: "object" = None
    # generic (high-cardinality) aggregation via the jitted sort-based
    # grouping kernels; off falls back to the numpy oracle path
    # (tidb_enable_tpu_exec sysvar)
    device_agg: bool = True
    # tables above this stream through staged batches on the dist scan
    # path instead of full device residency (tidb_device_cache_bytes)
    device_cache_bytes: int = 8 << 30
    # GROUP_CONCAT result truncation (group_concat_max_len sysvar)
    group_concat_max_len: int = 1024
    # device-resident hash-join build: pack+sort on device instead of a
    # host np.argsort round trip (tidb_tpu_join_device_build sysvar)
    join_device_build: bool = True
    # output tiles one fused join-expand dispatch may emit; bounds the
    # [T, C] buffer a many-many join materializes per dispatch
    # (tidb_tpu_join_tiles_per_dispatch sysvar)
    join_tiles: int = 8
    # probe strategy for the device join: off = searchsorted, auto =
    # hash table on TPU / searchsorted on CPU, xla/pallas force the
    # open-addressing table (tidb_tpu_join_probe_mode sysvar)
    join_probe_mode: str = "auto"
    # rows above which a fragment build side refuses to replicate and
    # the query falls back single-chip (tidb_broadcast_join_threshold_count)
    broadcast_rows_limit: int = 1 << 21
    # columnar segment store (ISSUE 8): scans over stored tables go
    # through encoded, zone-mapped segments (tidb_tpu_columnar_enable)
    columnar_enable: bool = True
    # fixed segment capacity in rows (tidb_tpu_segment_rows); the first
    # store built for a table pins its value
    segment_rows: int = 1 << 16
    # appended delta rows that trigger a coverage extension + zone-map
    # refresh at the next scan (tidb_tpu_segment_delta_rows)
    segment_delta_rows: int = 1 << 16
    # directory for spilled segment files (tidb_tpu_columnar_spill_dir;
    # empty = system tmp)
    columnar_spill_dir: str = ""
    # background delta->segment compaction (ISSUE 17): delta-depth
    # rebuilds run on a worker thread off the statement path instead of
    # inline at the next scan (tidb_tpu_compaction)
    compaction_enable: bool = True
    # pipelined device-resident execution (ISSUE 9): fuse eligible
    # scan->filter->project->partial-agg fragments into one jitted
    # program per chunk (tidb_tpu_pipeline_fuse)
    pipeline_fuse: bool = True
    # fused ORDER BY [+ LIMIT] roots (ISSUE 18): False routes the
    # statement to the classic materializing sort up front — plan
    # feedback flips it for digests whose observed LIMIT + offset
    # overflowed the device top-k capacity gate
    fused_topn: bool = True
    # staging chunks kept in flight ahead of compute by the prefetch
    # thread; 0 = stage inline (tidb_tpu_pipeline_prefetch_depth)
    prefetch_depth: int = 2
    # byte budget of the cross-statement device buffer cache; 0 = off
    # (tidb_tpu_device_buffer_cache_bytes)
    device_buffer_cache_bytes: int = 256 << 20
    # stage fragment inputs FoR-encoded in narrow dtypes, decoded inside
    # the fragment program (tidb_tpu_stage_encoded)
    stage_encoded: bool = True

    def __post_init__(self):
        if self.mem_tracker is None:
            from tidb_tpu.utils.memory import MemTracker

            self.mem_tracker = MemTracker("query")


class Executor:
    """Open/Next/Close — the same operator boundary as the reference's
    executor.Executor, pulling device Chunks instead of CPU chunks."""

    schema: List[PlanCol]

    def __init__(self, schema: List[PlanCol], children: List["Executor"]):
        self.schema = schema
        self.children = children
        self.stats = RuntimeStats()

    def open(self, ctx: ExecContext) -> None:
        for c in self.children:
            c.open(ctx)

    def next(self) -> Optional[Chunk]:
        raise NotImplementedError

    def close(self) -> None:
        for c in self.children:
            c.close()

    def chunks(self) -> Iterator[Chunk]:
        while True:
            ch = self.next()
            if ch is None:
                return
            yield ch


@dataclass
class ResultSet:
    names: List[str]
    rows: List[tuple]
    # column type kinds (tidb_tpu.types.TypeKind) for wire-protocol column
    # metadata; None for synthetic result sets (SHOW/EXPLAIN)
    types: Optional[list] = None
    # full SQLTypes (precision/scale preserved) when produced by a real
    # plan — CTAS derives its schema from these
    sql_types: Optional[list] = None
    # per-column string collation (from the plan column's dictionary)
    # so CTAS keeps the source's collation; None entries = non-string
    collations: Optional[list] = None

    def __len__(self):
        return len(self.rows)


def run_plan(root: Executor, ctx: ExecContext, n_visible: Optional[int] = None) -> ResultSet:
    """Drive an executor tree to completion and materialize host rows.

    Runs under host_eager(): the tree's glue ops (finalize, sort of a
    few groups, result decode) stay on the host CPU backend; only the
    compiled mesh fragments — whose inputs are committed device arrays —
    execute on the accelerator. Keeps device round-trips per query O(1)."""
    from tidb_tpu.utils.device import host_eager

    with host_eager():
        return _run_plan(root, ctx, n_visible)


def _run_plan(root: Executor, ctx: ExecContext, n_visible: Optional[int] = None) -> ResultSet:
    opened = False
    try:
        root.open(ctx)  # inside try: open() can raise after acquiring
        opened = True   # spill files / device buffers that close() frees
        visible = root.schema if n_visible is None else root.schema[:n_visible]
        uids = [c.uid for c in visible]
        dicts = {c.uid: c.dict_ for c in visible if c.dict_ is not None}
        rows: List[tuple] = []
        for ch in root.chunks():
            raise_if_cancelled(ctx)
            rows.extend(ch.to_pylist(dicts=dicts, names=uids))
        return ResultSet(
            names=[c.name for c in visible],
            rows=rows,
            types=[c.type_.kind for c in visible],
            sql_types=[c.type_ for c in visible],
            collations=[getattr(c.dict_, "collation", None)
                        for c in visible],
        )
    finally:
        try:
            root.close()
        except Exception:
            if opened:
                raise
