"""HashJoinExec (ref: executor/join.go — build + concurrent probe workers).

TPU redesign: hash tables are scatter-hostile, so the build side becomes a
*sorted* key array (+ row payload) on device, and each probe chunk runs
through the fused kernels in ops/join_kernels.py:

    probe_count:  key pack -> searchsorted -> match count -> prefix sum
    expand_tiles: [T, C] fixed-capacity output tiles per dispatch

The build phase is device-resident on the jitted tier: packed keys +
payload are staged once (padded to a power-of-two shape bucket) and the
pack + sort + payload gather run as ONE device program — no host
``np.argsort`` round trip. The host tier (``tidb_enable_tpu_exec`` off)
keeps its numpy probe and pays exactly one sort and one gather per
payload column.

The kernels live at module level in ops/join_kernels.py and take every
query-specific value as an argument, so a repeated join re-traces
NOTHING at steady state (``JOIN_COMPILE_TOTAL`` guards this; EXPLAIN
ANALYZE shows per-operator ``recompiles:``). The only host syncs per
probe chunk are the match total (to size the expansion) — everything
else stays on device.

Multi-key equi joins pack keys into one int64 using host-known ranges
(offset+stride per key); if ranges overflow int64, packing switches to
a 64-bit mixing hash of the composite key with exact on-device
verification — expanded candidate rows are filtered by real key
equality, so hash collisions only cost extra candidates, never wrong
results (the reference similarly falls back from its perfect-hash fast
path to a generic one).

Join kinds: inner, left (outer), semi, anti (with NOT IN null semantics:
any NULL build key -> empty result).
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tidb_tpu.chunk.chunk import Chunk
from tidb_tpu.chunk.column import Column
from tidb_tpu.executor.base import ExecContext, Executor, raise_if_cancelled
from tidb_tpu.ops import join_kernels as jk
from tidb_tpu.utils.jitcache import cached_jit
from tidb_tpu.expression.compiler import compile_predicate, eval_expr
from tidb_tpu.types import INT64, TypeKind

__all__ = ["HashJoinExec", "IndexJoinExec"]


def _pad_np(a: np.ndarray, cap: int, fill=0) -> np.ndarray:
    """Pad a host array to a shape-bucket capacity."""
    n = len(a)
    if n == cap:
        return a
    out = np.full(cap, fill, dtype=a.dtype)
    out[:n] = a
    return out


def _pad_dev(a, cap: int, fill=0):
    """Pad a (possibly device) array to a shape-bucket capacity."""
    n = a.shape[0]
    if n == cap:
        return a
    if isinstance(a, np.ndarray):
        return _pad_np(a, cap, fill)
    return jnp.concatenate([a, jnp.full(cap - n, fill, dtype=a.dtype)])


class HashJoinExec(Executor):
    def __init__(self, schema, probe_child, build_child, kind: str,
                 probe_keys: List, build_keys: List, other_cond=None,
                 probe_schema=None, build_schema=None, exists_sem: bool = False):
        super().__init__(schema, [probe_child, build_child])
        self.kind = kind
        self.probe_keys = probe_keys
        self.build_keys = build_keys
        self.other_cond = other_cond
        self.probe_schema = probe_schema
        self.build_schema = build_schema
        self.exists_sem = exists_sem

    # ------------------------------------------------------------------

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        self.ctx = ctx
        self._pending: List[Chunk] = []
        self._drained = False
        self._build()

    def _build(self):
        """Drain the build child; compact key + payload columns; then
        EITHER one host sort (host numpy tier — no device staging at
        all) OR one padded staging transfer + the fused device
        pack/sort/gather kernel (jitted tier)."""
        t0 = time.perf_counter()
        build_child = self.children[1]
        keys_ir = self.build_keys

        def eval_keys(chunk):
            # keyless (cross) join: a constant key matches everything
            if not keys_ir:
                z = jnp.zeros(chunk.capacity, dtype=jnp.int64)
                return [(z, jnp.ones(chunk.capacity, dtype=jnp.bool_))], chunk.sel
            outs = [eval_expr(k, chunk) for k in keys_ir]
            return outs, chunk.sel

        eval_keys = cached_jit("joinkeys", repr(keys_ir), lambda: eval_keys)

        def eval_keys_any(chunk):
            # numpy first: key exprs are almost always column refs /
            # dict lookups, and the jitted evaluator recompiles per
            # query (per-query uids in its closure)
            if not keys_ir:
                z = np.zeros(chunk.capacity, dtype=np.int64)
                return ([(z, np.ones(chunk.capacity, dtype=np.bool_))],
                        chunk.sel)
            outs = [self._np_eval_key(k, chunk) for k in keys_ir]
            if all(o is not None for o in outs):
                return outs, chunk.sel
            return eval_keys(chunk)

        key_cols = [[] for _ in (keys_ir or [None])]
        key_ok = []
        payload: dict = {c.uid: ([], []) for c in (self.build_schema or [])}
        for chunk in build_child.chunks():
            # KILL/deadline interrupts the build drain chunk-by-chunk
            raise_if_cancelled(self.ctx)
            outs, sel = eval_keys_any(chunk)
            sel = np.asarray(sel)
            live = np.nonzero(sel)[0]
            ok = np.ones(len(live), dtype=np.bool_)
            for i, (d, v) in enumerate(outs):
                key_cols[i].append(np.asarray(d)[live])
                ok &= np.asarray(v)[live]
            key_ok.append(ok)
            for uid in payload:
                col = chunk.columns[uid]
                payload[uid][0].append(np.asarray(col.data)[live])
                payload[uid][1].append(np.asarray(col.valid)[live])

        key_arrays = [np.concatenate(p) if p else np.zeros(0, dtype=np.int64) for p in key_cols]
        ok = np.concatenate(key_ok) if key_ok else np.zeros(0, dtype=np.bool_)
        self._build_had_null = bool((~ok).any())
        self._n_build = int(ok.sum())

        # pack parameters (and the hash-mode decision) come from the
        # VALID keys only — a NULL slot's garbage value must not blow
        # the range into hash mode
        valid_keys = [k[ok] for k in key_arrays]
        self._pack_info = self._key_pack_info(valid_keys)
        self._has_filter = self.other_cond is not None or self._hash_mode
        self._payload_uids = list(payload)
        self._build_schema_by_uid = {c.uid: c for c in (self.build_schema or [])}

        keep_np = self._host_probe_eligible()
        nbytes = 0
        tier = "host" if keep_np else "device"
        if keep_np:
            # host tier: ONE argsort and ONE gather per column — the
            # sorted arrays are derived once and never staged to device
            # (the numpy probe path is the only consumer; the
            # tidb_tpu_join_device_build=0 escape hatch shares
            # _host_firsts but pads to a jit shape bucket)
            packed = self._pack_host(valid_keys)
            order = np.argsort(packed, kind="stable")
            self._sorted_keys_np = packed[order]
            live_idx = np.flatnonzero(ok)[order]
            self._sorted_keys = None
            self._build_payload = {}
            self._build_payload_np = {}
            nbytes = self._sorted_keys_np.nbytes
            # direct-address probe index (radix histogram) for dense
            # packed domains: O(1) gathers beat per-element binary search
            dom = self._direct_domain(len(self._sorted_keys_np))
            self._firsts_np = None
            if dom is not None:
                lo, rng = dom
                self._firsts_np = self._host_firsts(
                    self._sorted_keys_np, lo, rng)
                self._direct_lo_np, self._direct_rng_np = lo, rng
                nbytes += self._firsts_np.nbytes
            for uid, (dlist, vlist) in payload.items():
                c = self._build_schema_by_uid[uid]
                d = (np.concatenate(dlist) if dlist
                     else np.zeros(0, dtype=c.type_.np_dtype))
                v = (np.concatenate(vlist) if vlist
                     else np.zeros(0, dtype=np.bool_))
                d, v = d[live_idx], v[live_idx]
                nbytes += d.nbytes + v.nbytes
                self._build_payload_np[uid] = (d, v)
        elif (getattr(self.ctx, "join_device_build", True)
                or self._hash_mode):
            # hash mode always builds on device: its packed keys only
            # exist there (the host combiner was retired with the old
            # double-sort build)
            nbytes = self._stage_device_build(key_arrays, ok, payload)
        else:
            # tidb_tpu_join_device_build = 0 escape hatch: sort on host,
            # stage the already-sorted arrays. The probe kernels are
            # identical — only the sort placement changes.
            nbytes = self._stage_host_sorted_build(key_arrays, ok, payload)
            tier = "host_sorted"
        # account the materialized build side against the query budget
        # (ref: HashJoinExec's build RowContainer under the memory tracker)
        self._mem_tracker = self.ctx.mem_tracker.child("hashjoin.build")
        self._build_bytes = int(nbytes)
        self._mem_tracker.consume(self._build_bytes)
        from tidb_tpu.utils.metrics import JOIN_BUILD_SECONDS

        JOIN_BUILD_SECONDS.observe(time.perf_counter() - t0, tier=tier)

    def close(self) -> None:
        if getattr(self, "_build_bytes", 0):
            self._mem_tracker.release(self._build_bytes)
            self._build_bytes = 0
        super().close()

    def _key_pack_info(self, key_arrays: List[np.ndarray]):
        """Pack parameters per key WITHOUT materializing packed keys
        (the jitted tier packs on device). Sets self._hash_mode; returns
        [(mode, lo, stride, rng), ...] or [("hash", modes)] when the
        range product overflows int64."""
        self._hash_mode = False
        modes = ["bits" if np.issubdtype(k.dtype, np.floating) else "int"
                 for k in key_arrays]
        if len(key_arrays) == 1:
            k = key_arrays[0]
            if modes[0] == "int" and len(k):
                # lo/rng of the packed domain feed the direct-address
                # index decision (the probe packer ignores them for
                # single keys, so recording real values is free)
                lo, hi = int(k.min()), int(k.max())
                rng = hi - lo + 1
                if rng >= (1 << 63):
                    # keys span (almost) the whole int64 domain: the rng
                    # itself doesn't fit int64 (the probe-param arrays
                    # would overflow). Direct indexing is ineligible
                    # anyway — record 0, the "unknown range" marker.
                    rng = 0
                return [(modes[0], lo, 1, rng)]
            return [(modes[0], 0, 1, 0)]
        conv = [k.astype(np.float64).view(np.int64) if m == "bits"
                else k.astype(np.int64) for k, m in zip(key_arrays, modes)]
        info = []
        stride = 1
        for k, mode in zip(conv, modes):
            lo = int(k.min()) if len(k) else 0
            hi = int(k.max()) if len(k) else 0
            rng = hi - lo + 1
            if rng <= 0 or rng * stride > (1 << 62):
                self._hash_mode = True
                return [("hash", tuple(modes))]
            info.append((mode, lo, stride, rng))
            stride *= rng
        return info

    # direct-address index ceilings: absolute (host/device memory for the
    # [rng + 1] prefix array) and relative to the build bucket (don't
    # mint a giant histogram for a tiny build over a sparse domain)
    DIRECT_ABS_LIMIT = 1 << 23
    DIRECT_REL_LIMIT = 32

    def _direct_domain(self, n_bucket: int):
        """(lo, rng) of the packed-key domain when the direct-address
        (radix histogram) probe index pays off, else None. Dense build
        keys — the PK-FK common case — resolve probes in O(1) gathers."""
        if self._hash_mode or self._n_build == 0:
            return None
        info = self._pack_info
        if len(info) == 1:
            mode, lo, _stride, rng = info[0]
            if mode != "int" or rng <= 0:
                return None
        else:
            lo = 0
            rng = info[-1][2] * info[-1][3]  # prod of per-key ranges
        if rng > min(self.DIRECT_ABS_LIMIT,
                     max(1 << 18, self.DIRECT_REL_LIMIT * n_bucket)):
            return None
        return lo, rng

    @staticmethod
    def _host_firsts(sorted_packed: np.ndarray, lo: int, rng: int,
                     pad_to: int = 0) -> np.ndarray:
        """The direct-address index, built on host: bincount + cumsum
        prefix array over the dense packed domain [lo, lo+rng). One
        definition for BOTH host consumers — the numpy probe tier
        (exact length) and the host_sorted escape hatch, whose jit
        consumer needs `pad_to` shape-bucket padding (fill = n so
        out-of-domain gathers read an empty range). The device twin is
        ops/join_kernels.build_direct_index."""
        counts = np.bincount(sorted_packed - lo, minlength=rng)
        firsts = np.concatenate([np.zeros(1, dtype=np.int64),
                                 np.cumsum(counts, dtype=np.int64)])
        if pad_to > rng:
            firsts = _pad_np(firsts, pad_to + 1, len(sorted_packed))
        return firsts

    def _pack_host(self, key_arrays: List[np.ndarray]) -> np.ndarray:
        """Range-pack valid build keys on host (host tier only; hash
        mode never reaches here — it forces the jitted path)."""
        info = self._pack_info
        if len(key_arrays) == 1:
            return self._np_as_int64(key_arrays[0], info[0][0])
        packed = np.zeros(len(key_arrays[0]), dtype=np.int64)
        for k, (mode, lo, stride, rng) in zip(key_arrays, info):
            packed = packed + (self._np_as_int64(k, mode) - lo) * stride
        return packed

    def _resolve_probe_table(self) -> int:
        """Resolve the probe strategy (tidb_tpu_join_probe_mode via
        hash_probe.resolve_mode — trace-time platform aware) and build
        the open-addressing table ONCE over the staged sorted keys when
        the table path is selected. Dense packed domains keep the O(1)
        direct-address index instead (it beats any hash walk), and
        over-capacity builds fall back to searchsorted. Returns the
        table's resident bytes for the memory tracker."""
        from tidb_tpu.ops import hash_probe as hp

        self._probe_mode = hp.resolve_mode(
            getattr(self.ctx, "join_probe_mode", "off"))
        self._probe_table = None
        if self._probe_mode == "sorted" or self._direct:
            self._probe_mode = "sorted"
            return 0
        t = jk.build_hash_table(self._sorted_keys)
        if t is None:  # build side exceeds the VMEM capacity envelope
            self._probe_mode = "sorted"
            return 0
        self._probe_table = t
        return int(sum(a.nbytes for a in t[:3]))

    def _set_probe_pack_params(self, nk: int) -> None:
        """Device copies of the pack parameters the probe kernel takes
        as traced args (modes stay static)."""
        info = self._pack_info
        if self._hash_mode:
            self._modes = tuple(info[0][1])
            los = strides = rngs = np.zeros(nk, dtype=np.int64)
        else:
            self._modes = tuple(e[0] for e in info)
            los = np.asarray([e[1] for e in info], dtype=np.int64)
            strides = np.asarray([e[2] for e in info], dtype=np.int64)
            rngs = np.asarray([e[3] for e in info], dtype=np.int64)
        self._los = jnp.asarray(los)
        self._strides = jnp.asarray(strides)
        self._rngs = jnp.asarray(rngs)

    def _stage_host_sorted_build(self, key_arrays, ok, payload) -> int:
        """tidb_tpu_join_device_build = 0 escape hatch: the build sorts
        on host (one argsort + one gather per column, like the numpy
        tier) and the SORTED arrays stage to device for the same fused
        probe kernels. Correctness-identical to the device build."""
        from tidb_tpu.utils import dispatch as dsp

        self._set_probe_pack_params(len(key_arrays))
        valid_keys = [k[ok] for k in key_arrays]
        packed = self._pack_host(valid_keys)
        order = np.argsort(packed, kind="stable")
        sorted_np = packed[order]
        live_idx = np.flatnonzero(ok)[order]
        n = len(sorted_np)
        B = jk.shape_bucket(n)
        # padding must keep the array sorted: dead slots -> INT64_MAX
        self._sorted_keys = jnp.asarray(
            _pad_np(sorted_np, B, np.iinfo(np.int64).max))
        self._n_build_dev = jnp.asarray(n, dtype=jnp.int64)
        self._sorted_keys_np = None
        self._build_payload_np = {}
        self._build_keyvals_dev = ()  # hash mode never takes this path
        self._build_payload = {}
        nbytes = self._sorted_keys.nbytes
        n_staged = 1
        for uid in self._payload_uids:
            dlist, vlist = payload[uid]
            c = self._build_schema_by_uid[uid]
            d = (np.concatenate(dlist) if dlist
                 else np.zeros(0, dtype=c.type_.np_dtype))
            v = (np.concatenate(vlist) if vlist
                 else np.zeros(0, dtype=np.bool_))
            dd = jnp.asarray(_pad_np(d[live_idx], B))
            vv = jnp.asarray(_pad_np(v[live_idx], B, False))
            self._build_payload[uid] = (dd, vv)
            nbytes += dd.nbytes + vv.nbytes
            n_staged += 2
        dom = self._direct_domain(B)
        self._direct = dom is not None
        if self._direct:
            lo, rng = dom
            # bucket the histogram length like the device build does, or
            # the probe kernel would re-trace per build data size
            self._firsts = jnp.asarray(self._host_firsts(
                sorted_np, lo, rng,
                pad_to=jk.shape_bucket(rng, floor=64)))
            self._direct_lo, self._direct_rng = lo, rng
            n_staged += 1
        else:
            self._firsts = jnp.zeros(2, dtype=jnp.int64)
            self._direct_lo = self._direct_rng = 0
        nbytes += self._firsts.nbytes
        nbytes += self._resolve_probe_table()
        dsp.record(n_staged, site="stage")
        return nbytes

    def _stage_device_build(self, key_arrays, ok, payload) -> int:
        """Pad to a power-of-two shape bucket, stage ONCE, and run the
        fused pack+sort+gather kernel — the build side becomes
        device-resident sorted arrays with NULL/dead keys at the tail.
        Returns resident bytes for the memory tracker."""
        from tidb_tpu.utils import dispatch as dsp

        nk = len(key_arrays)
        self._set_probe_pack_params(nk)
        B = jk.shape_bucket(len(ok))
        ok_p = jnp.asarray(_pad_np(ok, B, False))
        kd = tuple(jnp.asarray(_pad_np(np.asarray(k), B)) for k in key_arrays)
        kv = (ok_p,) * nk  # key validity is already folded into ok
        pd, pv = [], []
        for uid in self._payload_uids:
            dlist, vlist = payload[uid]
            c = self._build_schema_by_uid[uid]
            d = (np.concatenate(dlist) if dlist
                 else np.zeros(0, dtype=c.type_.np_dtype))
            v = (np.concatenate(vlist) if vlist
                 else np.zeros(0, dtype=np.bool_))
            pd.append(jnp.asarray(_pad_np(d, B)))
            pv.append(jnp.asarray(_pad_np(v, B, False)))
        dsp.record(1 + nk + 2 * len(pd), site="stage")

        sorted_keys, n_build_dev, out_d, out_v, out_k = jk.build_sort(
            kd, kv, ok_p, tuple(pd), tuple(pv),
            self._los, self._strides, self._rngs,
            modes=self._modes, hash_mode=self._hash_mode)
        self._sorted_keys = sorted_keys
        self._n_build_dev = n_build_dev
        # direct-address probe index over a dense packed domain, built on
        # device from the sorted keys (shape-bucketed so repeats reuse
        # the compiled histogram kernel)
        dom = self._direct_domain(B)
        self._direct = dom is not None
        if self._direct:
            lo, rng = dom
            rng_bucket = jk.shape_bucket(rng, floor=64)
            self._firsts = jk.build_direct_index(
                sorted_keys, n_build_dev, lo, rng_bucket)
            self._direct_lo, self._direct_rng = lo, rng
        else:
            self._firsts = jnp.zeros(2, dtype=jnp.int64)
            self._direct_lo = self._direct_rng = 0
        self._sorted_keys_np = None
        self._build_payload_np = {}
        self._build_payload = {
            uid: (d, v)
            for uid, d, v in zip(self._payload_uids, out_d, out_v)
        }
        # raw key values build-sorted: exact verification of
        # hash-expanded candidate rows reads them (passed as kernel
        # ARGS, never closure state — see _match_filter)
        self._build_keyvals_dev = out_k if self._hash_mode else ()
        nbytes = sorted_keys.nbytes + self._firsts.nbytes
        nbytes += self._resolve_probe_table()
        for d, v in zip(out_d, out_v):
            nbytes += d.nbytes + v.nbytes
        for k in self._build_keyvals_dev:
            nbytes += k.nbytes
        return nbytes

    # deferred-sync window for the device probe: per-chunk match totals
    # accumulate as device scalars and resolve in ONE batched fetch per
    # window instead of one int() sync per chunk (ISSUE 9). The byte cap
    # bounds how many probe chunks (plus their count arrays) stay
    # referenced on device while their totals are in flight.
    PROBE_SYNC_CHUNKS = 8
    PROBE_DEFER_BYTES = 128 << 20

    def next(self) -> Optional[Chunk]:
        while True:
            if self._pending:
                return self._pending.pop(0)
            if self._drained:
                return None
            self._fill_pending()

    def _fill_pending(self) -> None:
        """Pull probe chunks until output lands in _pending or the child
        drains. Device-tier chunks needing a match total (inner/left,
        filtered semi/anti) DEFER it: probe_count results queue with
        their device totals, and one batched device_get per window
        resolves every queued chunk — the probe phase of a fragment now
        syncs O(chunks / window), not O(chunks)."""
        deferred: List[dict] = []
        dbytes = 0
        while not self._pending and not self._drained:
            chunk = self.children[0].next()
            if chunk is None:
                self._drained = True
                break
            # a KILL/deadline must interrupt the probe drain between
            # device steps, not wait for the root chunk loop
            raise_if_cancelled(self.ctx)
            if self._host_probe_eligible():
                self._process_probe_chunk_np(chunk)
                continue
            tok = self._probe_start_device(chunk)
            if tok is None:
                continue  # fully handled (unfiltered semi/anti)
            deferred.append(tok)
            # the window pins the chunk's columns AND the probe_count
            # results: 4 int64 + 2 bool [Rp] arrays per token
            dbytes += sum(c.data.nbytes + c.valid.nbytes
                          for c in chunk.columns.values())
            dbytes += tok["Rp"] * 34
            if (len(deferred) >= self.PROBE_SYNC_CHUNKS
                    or dbytes >= self.PROBE_DEFER_BYTES):
                self._probe_finish_batch(deferred)
                deferred = []
                dbytes = 0
        if deferred:
            self._probe_finish_batch(deferred)

    def _host_probe_eligible(self) -> bool:
        """The numpy probe path covers the workhorse shapes on the host
        engine (ctx.device_agg off): direct-address gathers (or binary
        search) + exact np.repeat expansion with no staging at all.
        Left joins and filtered/hash-verified probes take the fused
        device kernels (NULL padding + re-verification logic)."""
        return (not getattr(self.ctx, "device_agg", True)
                and self.kind in ("inner", "semi", "anti")
                and self.other_cond is None
                and not self._hash_mode)

    @staticmethod
    def _keep_unmatched(sel, ok, matched, build_had_null, exists_sem):
        """Anti-join keep mask, shared (semantically) with the jitted
        path: NOT EXISTS keeps NULL-key probe rows; NOT IN goes empty
        when the build side held a NULL key (caller handles that)."""
        if exists_sem:
            return sel & ~(ok & matched)
        return sel & ok & ~matched

    def _np_eval_key(self, e, chunk: Chunk):
        """Numpy (data, valid) for the key shapes the host path meets —
        column refs, literals, dictionary Lookups. Returns None for
        anything else (caller falls back to the jitted evaluator).
        Evaluating keys without jax matters: a per-join jax.jit keyed on
        per-query uids recompiled EVERY query (~20ms per join — the
        fixed cost that made every small host join cost ~30ms)."""
        from tidb_tpu.expression.expr import ColumnRef, Literal, Lookup

        if isinstance(e, ColumnRef):
            col = chunk.columns[e.name]
            return np.asarray(col.data), np.asarray(col.valid)
        if isinstance(e, Literal):
            cap = chunk.capacity
            dt = e.type_.np_dtype  # match the jitted evaluator's dtype:
            # pack-mode selection ('bits' for floats) depends on it
            if e.value is None:
                return (np.zeros(cap, dtype=dt),
                        np.zeros(cap, dtype=np.bool_))
            return (np.full(cap, e.value, dtype=dt),
                    np.ones(cap, dtype=np.bool_))
        if isinstance(e, Lookup):
            base = self._np_eval_key(e.arg, chunk)
            if base is None:
                return None
            data, valid = base
            table = np.asarray(e.table, dtype=e.type_.np_dtype)
            if len(table) == 0:  # empty dictionary: every code is absent
                return (np.zeros(len(data), dtype=e.type_.np_dtype),
                        np.zeros(len(data), dtype=np.bool_))
            idx = np.clip(data.astype(np.int64), 0, len(e.table) - 1)
            out = table[idx]
            if e.table_valid is not None:
                tv = np.asarray(e.table_valid, dtype=np.bool_)
                valid = valid & tv[idx]
            valid = valid & (data >= 0) & (data < len(e.table))
            return out, valid
        return None

    @staticmethod
    def _np_as_int64(d: np.ndarray, mode: str) -> np.ndarray:
        if mode == "bits":
            return d.astype(np.float64).view(np.int64)
        return d.astype(np.int64)

    def _np_pack_probe(self, outs):
        """Numpy mirror of the device packer (range packing; hash mode never
        reaches the numpy path — _host_probe_eligible excludes it)."""
        info = self._pack_info
        if len(outs) == 1:
            d, v = outs[0]
            return (self._np_as_int64(d, info[0][0]), v,
                    np.ones_like(v, dtype=np.bool_))
        packed = np.zeros(len(outs[0][0]), dtype=np.int64)
        valid = np.ones(len(outs[0][0]), dtype=np.bool_)
        in_range = np.ones_like(valid)
        for (d, v), (mode, lo, stride, rng) in zip(outs, info):
            d = self._np_as_int64(d, mode)
            valid = valid & v
            in_range = in_range & (d >= lo) & (d < lo + rng)
            packed = packed + np.clip(d - lo, 0, max(rng - 1, 0)) * stride
        return packed, valid, in_range

    def _probe_key_arrays(self, chunk: Chunk, host: bool = True):
        """(key datas, key valids) for one probe chunk.

        ``host=True`` (the numpy tier): pure numpy when the key exprs
        allow it (almost always — column refs / dictionary lookups),
        else the cached jitted evaluator.

        ``host=False`` (the device tier): plain ColumnRef keys pass
        their arrays through UNTOUCHED — a device-resident column must
        not detour through np.asarray (a synchronous device->host
        round trip per probe chunk on real hardware); anything else
        evaluates in one cached jitted kernel per key-expr repr
        (reused across executions; binder uids are deterministic)."""
        if not self.probe_keys:
            return (), ()
        if not host:
            from tidb_tpu.expression.expr import ColumnRef

            if all(isinstance(k, ColumnRef) for k in self.probe_keys):
                cols = [chunk.columns[k.name] for k in self.probe_keys]
                return (tuple(c.data for c in cols),
                        tuple(c.valid for c in cols))
        elif getattr(self, "_probe_key_mode", None) != "jit":
            outs = [self._np_eval_key(k, chunk) for k in self.probe_keys]
            if all(o is not None for o in outs):
                self._probe_key_mode = "np"
                return (tuple(o[0] for o in outs),
                        tuple(o[1] for o in outs))
            self._probe_key_mode = "jit"
        if getattr(self, "_probe_key_fn", None) is None:
            keys_ir = self.probe_keys

            def keyfn(ch):
                return tuple(tuple(eval_expr(k, ch)) for k in keys_ir)

            self._probe_key_fn = cached_jit(
                "joinprobekeys", repr(keys_ir), lambda: keyfn)
        outs = self._probe_key_fn(chunk)
        return tuple(o[0] for o in outs), tuple(o[1] for o in outs)

    def _np_probe_keys(self, chunk: Chunk):
        """Key eval + pack for the numpy probe path."""
        if not self.probe_keys:
            cap = chunk.capacity
            return (np.zeros(cap, dtype=np.int64), np.asarray(chunk.sel),
                    np.ones(cap, dtype=np.bool_))
        kd, kv = self._probe_key_arrays(chunk)
        outs = [(np.asarray(d), np.asarray(v)) for d, v in zip(kd, kv)]
        packed, valid, in_r = self._np_pack_probe(outs)
        return packed, valid & np.asarray(chunk.sel), in_r

    def _process_probe_chunk_np(self, chunk: Chunk):
        from tidb_tpu.utils.metrics import JOIN_PROBE_MODE_TOTAL

        JOIN_PROBE_MODE_TOTAL.inc(mode="host")
        packed, ok, in_r = self._np_probe_keys(chunk)
        if self._firsts_np is not None:
            # dense packed domain: O(1) gathers into the radix histogram
            idx = packed - self._direct_lo_np
            in_r = in_r & (idx >= 0) & (idx < self._direct_rng_np)
            idx = np.clip(idx, 0, self._direct_rng_np - 1)
            start = self._firsts_np[idx]
            count = np.where(ok & in_r, self._firsts_np[idx + 1] - start, 0)
        else:
            sk = self._sorted_keys_np
            start = np.searchsorted(sk, packed, side="left")
            end = np.searchsorted(sk, packed, side="right")
            count = np.where(ok & in_r, end - start, 0)

        if self.kind in ("semi", "anti"):
            matched = count > 0
            if self.kind == "semi":
                self._pending.append(chunk.with_sel(jnp.asarray(ok & matched)))
                return
            if self._build_had_null and not self.exists_sem:
                return  # NOT IN with NULL in subquery: no row is ever TRUE
            keep = self._keep_unmatched(np.asarray(chunk.sel), ok, matched,
                                        self._build_had_null, self.exists_sem)
            self._pending.append(chunk.with_sel(jnp.asarray(keep)))
            return

        total = int(count.sum())
        if self.kind == "inner":  # host path is unfiltered by
            # eligibility; the exact output count is already host-side
            self.stats.add_out_rows(total)
        if total == 0:
            return
        cum = np.cumsum(count)
        cum_excl = cum - count
        cap = self.ctx.chunk_capacity
        build_schema = {c.uid: c for c in (self.build_schema or [])}
        probe_np = {uid: (np.asarray(col.data), np.asarray(col.valid))
                    for uid, col in chunk.columns.items()}
        # columns with no NULLs skip the validity gather entirely (scan
        # output is usually all-valid; from_numpy mints the ones mask)
        all_valid = {uid: bool(v.all()) for uid, (d, v) in probe_np.items()}
        ball_valid = {uid: bool(v.all())
                      for uid, (d, v) in self._build_payload_np.items()}
        types = {uid: chunk.columns[uid].type_ for uid in probe_np}
        types.update({uid: build_schema[uid].type_
                      for uid in self._build_payload_np})
        # window the EXPANSION itself (not just the emission): a
        # many-to-many join's full expansion can dwarf host memory
        rows_of_window = np.searchsorted(cum, np.arange(0, total, cap),
                                         side="right")
        for wi, w in enumerate(range(0, total, cap)):
            hi = min(w + cap, total)
            m = hi - w
            lo_row = rows_of_window[wi]
            hi_row = int(np.searchsorted(cum, hi - 1, side="right"))
            rows = np.arange(lo_row, hi_row + 1)
            reps = np.minimum(cum[rows], hi) - np.maximum(cum_excl[rows], w)
            probe_row = np.repeat(rows, reps)
            # one repeat of the per-row offset replaces two per-output
            # gathers: build_pos = j + (start[row] - cum_excl[row])
            build_pos = (np.arange(w, hi, dtype=np.int64)
                         + np.repeat(start[rows] - cum_excl[rows], reps))
            arrays, valids = {}, {}
            for uid, (d, v) in probe_np.items():
                arrays[uid] = d[probe_row]
                if not all_valid[uid]:
                    valids[uid] = v[probe_row]
            for uid, (d, v) in self._build_payload_np.items():
                arrays[uid] = d[build_pos]
                if not ball_valid[uid]:
                    valids[uid] = v[build_pos]
            ccap = 8
            while ccap < m:
                ccap *= 2
            self._pending.append(
                Chunk.from_numpy(arrays, types, valids=valids, capacity=ccap))

    def _probe_finish_batch(self, tokens: List[dict]) -> None:
        """Resolve a deferred window: ONE device_get moves every queued
        chunk's match total, then each chunk finishes (expansion /
        qualification) with its now-host-known size."""
        # THE intentional probe sync, batched: one fetch of the
        # accumulated per-chunk match totals per deferred window
        # (PROBE_SYNC_CHUNKS chunks), replacing the per-chunk int()
        # round trip this loop used to pay; the totals size the tile
        # expansions (sanctioned device_get outside any loop — the
        # chunk-loop sync-budget pass watches the loop form)
        from tidb_tpu.utils import dispatch as dsp

        totals = dsp.record_fetch(
            jax.device_get([t["total_dev"] for t in tokens]))
        dsp.record(site="fetch")
        if self.kind == "inner" and not self._has_filter:
            # plan feedback: for the unfiltered inner join the summed
            # match totals ARE the output cardinality, host-known from
            # the fetch this loop already pays — record it for free
            self.stats.add_out_rows(int(sum(int(t) for t in totals)))
        for tok, total in zip(tokens, totals):
            try:
                self._probe_finish(tok, int(total))
            finally:
                from tidb_tpu.utils.metrics import JOIN_PROBE_SECONDS

                # spans launch -> expansion incl. any deferral wait;
                # overlapped chunks legitimately overlap their windows
                JOIN_PROBE_SECONDS.observe(time.perf_counter() - tok["t0"],
                                           kind=self.kind)

    def _probe_start_device(self, chunk: Chunk) -> Optional[dict]:
        """Launch the fused probe_count for one chunk. Unfiltered
        semi/anti joins finish here (their keep mask needs no total);
        everything else returns a deferral token carrying the device
        results, resolved later by _probe_finish_batch."""
        t0 = time.perf_counter()
        # hash-packed keys need exact re-verification of every candidate
        # row, so they take the same filtered paths as other_cond
        has_filter = self._has_filter
        key_datas, key_valids = self._probe_key_arrays(chunk, host=False)
        cap = chunk.capacity
        Rp = jk.shape_bucket(cap)
        sel = chunk.sel
        if Rp != cap:  # shape-bucket the probe: pad keys + sel to pow2
            key_datas = tuple(_pad_dev(d, Rp) for d in key_datas)
            key_valids = tuple(_pad_dev(v, Rp, False) for v in key_valids)
            sel = _pad_dev(sel, Rp, False)
        left_pad = self.kind == "left" and not has_filter
        start, count, real_count, cum, total_dev, ok, matched = jk.probe_count(
            self._sorted_keys, self._n_build_dev, key_datas, key_valids,
            sel, self._los, self._strides, self._rngs,
            self._firsts, self._direct_lo, self._direct_rng,
            modes=self._modes, hash_mode=self._hash_mode,
            left_pad=left_pad, direct=self._direct,
            table=self._probe_table, probe=self._probe_mode)

        if self.kind in ("semi", "anti") and not has_filter:
            if Rp != cap:
                matched = matched[:cap]
            okc = ok[:cap] if Rp != cap else ok
            if self.kind == "semi":
                self._pending.append(chunk.with_sel(okc & matched))
            elif self._build_had_null and not self.exists_sem:
                pass  # NOT IN with NULL in subquery: no row is ever TRUE
            elif self.exists_sem:
                # NOT EXISTS: a NULL probe key never matches -> row kept
                self._pending.append(
                    chunk.with_sel(chunk.sel & ~(okc & matched)))
            else:
                self._pending.append(
                    chunk.with_sel(chunk.sel & okc & ~matched))
            from tidb_tpu.utils.metrics import JOIN_PROBE_SECONDS

            JOIN_PROBE_SECONDS.observe(time.perf_counter() - t0,
                                       kind=self.kind)
            return None
        return {"chunk": chunk, "start": start, "count": count,
                "real_count": real_count, "cum": cum,
                "total_dev": total_dev, "ok": ok, "matched": matched,
                "cap": cap, "Rp": Rp, "t0": t0}

    def _probe_finish(self, tok: dict, total: int) -> None:
        """Complete one deferred probe chunk with its host-known match
        total: qualification for filtered semi/anti, tile expansion for
        inner/left."""
        chunk = tok["chunk"]
        start, count, real_count = tok["start"], tok["count"], \
            tok["real_count"]
        cum, ok = tok["cum"], tok["ok"]
        cap, Rp = tok["cap"], tok["Rp"]
        has_filter = self._has_filter

        if self.kind in ("semi", "anti"):  # has_filter: qualified path
            matched = self._qualified_matches(
                chunk, start, real_count, cum, total)
            okc = ok[:cap] if Rp != cap else ok
            if self.kind == "semi":
                self._pending.append(chunk.with_sel(okc & matched))
                return
            if self._build_had_null and not self.exists_sem:
                return  # NOT IN with NULL in subquery: no row is ever TRUE
            if self.exists_sem:
                # NOT EXISTS: a NULL probe key never matches -> row kept
                keep = chunk.sel & ~(okc & matched)
            else:
                keep = chunk.sel & okc & ~matched
            self._pending.append(chunk.with_sel(keep))
            return

        left_other = self.kind == "left" and has_filter
        if total == 0 and not left_other:
            return
        matched_np = (np.zeros(cap, dtype=np.bool_) if left_other else None)
        for out in self._expand_windows(chunk, start, count, real_count,
                                        cum, total, bookkeeping=has_filter):
            if has_filter:
                out = self._match_filter(out)
                if left_other:
                    osel = np.asarray(out.sel)
                    rows = np.asarray(
                        out.columns["__probe_row__"].data)[osel]
                    matched_np[rows] = True
                # bookkeeping columns stay internal to the match tracking
                out = Chunk(
                    {u: c for u, c in out.columns.items()
                     if u not in ("__probe_row__", "__build_pos__")},
                    out.sel,
                )
            self._pending.append(out)
        if left_other:
            # probe rows whose every match failed other_cond (or that had
            # none) emit one NULL-payload row each, per LEFT JOIN semantics
            unmatched = chunk.sel & jnp.asarray(~matched_np)
            # host-sync: intentional sync on the left-join + other_cond
            # tail — one bool per chunk decides whether a NULL-pad
            # chunk is emitted at all
            if bool(np.asarray(unmatched).any()):
                self._pending.append(self._null_build_chunk(chunk, unmatched))

    def _expand_windows(self, chunk: Chunk, start, count, real_count, cum,
                        total: int, bookkeeping: bool):
        """Yield output Chunks of capacity ctx.chunk_capacity via fused
        [T, C] tile dispatches — up to ctx.join_tiles output tiles per
        device round trip instead of one dispatch per window."""
        C = self.ctx.chunk_capacity
        max_tiles = max(1, getattr(self.ctx, "join_tiles", 8))
        p_uids = list(chunk.columns.keys())
        p_datas = tuple(chunk.columns[u].data for u in p_uids)
        p_valids = tuple(chunk.columns[u].valid for u in p_uids)
        b_uids = self._payload_uids
        b_datas = tuple(self._build_payload[u][0] for u in b_uids)
        b_valids = tuple(self._build_payload[u][1] for u in b_uids)
        w0 = 0
        while w0 < total:
            rem = -(-(total - w0) // C)  # ceil-div: tiles still needed
            T = min(jk.shape_bucket(rem, floor=1), max_tiles)
            out_p, out_b, sel_t, prow, bpos = jk.expand_tiles(
                start, count, real_count, cum, w0, p_datas, p_valids,
                b_datas, b_valids, n_tiles=T, tile_cap=C,
                build_cap=self._sorted_keys.shape[0],
                left=self.kind == "left",
                with_probe_row=bookkeeping,
                with_build_pos=bookkeeping and self._hash_mode)
            for i in range(min(T, rem)):
                cols = {}
                for u, (d2, v2) in zip(p_uids, out_p):
                    cols[u] = Column(d2[i], v2[i], chunk.columns[u].type_)
                for u, (d2, v2) in zip(b_uids, out_b):
                    cols[u] = Column(d2[i], v2[i],
                                     self._build_schema_by_uid[u].type_)
                if prow is not None:
                    cols["__probe_row__"] = Column(prow[i], sel_t[i], INT64)
                if bpos is not None:
                    cols["__build_pos__"] = Column(bpos[i], sel_t[i], INT64)
                yield Chunk(cols, sel_t[i])
            w0 += T * C

    def _qualified_matches(self, chunk: Chunk, start, count, cum,
                           total: int):
        """[capacity] bool: probe rows with at least one build match passing
        other_cond — via windowed expansion (semi/anti joins carrying extra
        conditions, e.g. decorrelated EXISTS with non-equi predicates)."""
        matched = np.zeros(chunk.capacity, dtype=np.bool_)
        for out in self._expand_windows(chunk, start, count, count, cum,
                                        total, bookkeeping=True):
            out = self._match_filter(out)
            osel = np.asarray(out.sel)
            rows = np.asarray(out.columns["__probe_row__"].data)[osel]
            matched[rows] = True
        return jnp.asarray(matched)

    def _match_filter(self, out: Chunk) -> Chunk:
        """Filter expanded candidate rows: exact key equality when the
        keys were hash-packed, then other_cond if present. The compiled
        fn is cached across queries by expr repr; the build key values
        are ARGS (not closure state), so a cache hit can never read a
        stale build side."""
        if getattr(self, "_filter_fn", None) is None:
            other = (compile_predicate(self.other_cond)
                     if self.other_cond is not None else None)
            hash_mode = self._hash_mode
            probe_keys = self.probe_keys
            modes = self._pack_info[0][1] if hash_mode else ()

            def fn(ch, keyvals):
                keep = ch.sel
                if hash_mode:
                    pos = ch.columns["__build_pos__"].data
                    for k_ir, mode, bv in zip(probe_keys, modes, keyvals):
                        pv = jk.as_int64_key(eval_expr(k_ir, ch)[0], mode)
                        keep = keep & (jnp.take(bv, pos, mode="clip") == pv)
                if other is not None:
                    keep = keep & other(ch)
                return ch.with_sel(keep)

            self._filter_fn = cached_jit(
                "joinfilter",
                f"{hash_mode}:{modes}:{self.probe_keys!r}"
                f":{self.other_cond!r}",
                lambda: fn)
        return self._filter_fn(out, tuple(
            getattr(self, "_build_keyvals_dev", ())))

    def _null_build_chunk(self, chunk: Chunk, sel) -> Chunk:
        """Probe columns pass through; build payload is all-NULL."""
        build_schema = {c.uid: c for c in (self.build_schema or [])}
        cols = dict(chunk.columns)
        for uid in self._build_payload:
            c = build_schema[uid]
            cols[uid] = Column(
                np.zeros(chunk.capacity, dtype=c.type_.np_dtype),
                np.zeros(chunk.capacity, dtype=np.bool_),
                c.type_,
            )
        return Chunk(cols, sel)

class IndexJoinExec(Executor):
    """Index-lookup join (ref: executor's IndexLookUpJoin; SURVEY.md:91):
    the inner side is never scanned — each outer chunk's join keys are
    batch-binary-searched against the inner table's sorted index cache
    (the same substrate PointGet/IndexRangeScan probe), candidate rows
    pass MVCC visibility, and matches gather straight from table
    storage. O((outer + matches) log n) host work, independent of the
    inner table's size — the access-path alternative the cascades memo
    costs against the hash join's exchange + build."""

    def __init__(self, schema, outer: Executor, eq_outer, inner_table,
                 index_name, inner_schema, inner_cond, other_cond):
        super().__init__(schema, [outer])
        self.eq_outer = eq_outer
        self.inner_table = inner_table
        self.index_name = index_name
        self.inner_schema = inner_schema
        self.inner_cond = inner_cond
        self.other_cond = other_cond

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        self.ctx = ctx
        from tidb_tpu.expression.compiler import compile_expr

        self._key_fns = [compile_expr(e) for e in self.eq_outer]
        self._pending: List[Chunk] = []
        self._skeys, self._srows = self.inner_table._sorted_index(
            self.index_name)
        self._resid = None
        if self.inner_cond is not None or self.other_cond is not None:
            conds = [c for c in (self.inner_cond, self.other_cond)
                     if c is not None]
            self._resid = [compile_predicate(c) for c in conds]

    def next(self) -> Optional[Chunk]:
        while True:
            if self._pending:
                return self._pending.pop(0)
            ch = self.children[0].next()
            if ch is None:
                return None
            self._join_chunk(ch)

    def _join_chunk(self, ch: Chunk) -> None:
        sel = np.asarray(ch.sel)
        live = np.nonzero(sel)[0]
        if len(live) == 0:
            return
        skeys, srows = self._skeys, self._srows
        nkeys = len(self._key_fns)
        i64 = np.iinfo(np.int64)
        # the index may be wider than the join key set (a composite pk
        # probed on its prefix): floor/ceil the suffix fields so the
        # whole equal-prefix run matches, not just suffix == 0
        probe_lo = np.zeros(len(live), dtype=skeys.dtype)
        probe_hi = np.zeros(len(live), dtype=skeys.dtype)
        for name in skeys.dtype.names[nkeys:]:
            probe_lo[name] = i64.min
            probe_hi[name] = i64.max
        kvalid = np.ones(len(live), dtype=np.bool_)
        for i, fn in enumerate(self._key_fns):
            col = fn(ch)
            kvalid &= np.asarray(col.valid)[live]
            keys = np.asarray(col.data)[live].astype(np.int64)
            probe_lo[f"k{i}"] = keys
            probe_hi[f"k{i}"] = keys
        # NULL keys match nothing; searchsorted over the composite tuple
        # gives the exact equality run — no hashing, no collisions
        lo = np.searchsorted(skeys, probe_lo, side="left")
        hi = np.searchsorted(skeys, probe_hi, side="right")
        counts = np.where(kvalid, hi - lo, 0)
        total = int(counts.sum())
        if total == 0:
            return
        outer_pos = np.repeat(np.arange(len(live)), counts)
        starts = np.repeat(lo, counts)
        offs = np.arange(total) - np.repeat(
            np.cumsum(counts) - counts, counts)
        cand = srows[starts + offs]
        vis = self.inner_table._mvcc_mask(
            cand, read_ts=self.ctx.read_ts, marker=self.ctx.txn_marker)
        cand = cand[vis]
        outer_rows = live[outer_pos[vis]]
        # windowed emission: expansion is bounded to chunk_capacity per
        # output chunk (the HashJoinExec contract), so a many-match key
        # set cannot spike host memory or mint giant downstream shapes
        win = max(self.ctx.chunk_capacity, 8)
        for s0 in range(0, len(cand), win):
            self._emit(ch, outer_rows[s0:s0 + win], cand[s0:s0 + win])

    def _emit(self, ch: Chunk, outer_rows, cand) -> None:
        if len(cand) == 0:
            return
        cap = 8
        while cap < len(cand):
            cap *= 2
        cols = {}
        for c in self.inner_schema:
            d = self.inner_table.data[c.name][cand]
            v = self.inner_table.valid[c.name][cand]
            cols[c.uid] = Column.from_numpy(d, c.type_, valid=v,
                                            capacity=cap)
        for uid, col in ch.columns.items():
            d = np.asarray(col.data)[outer_rows]
            v = np.asarray(col.valid)[outer_rows]
            cols[uid] = Column.from_numpy(d, col.type_, valid=v,
                                          capacity=cap)
        osel = np.zeros(cap, dtype=np.bool_)
        osel[: len(cand)] = True
        out = Chunk(cols, osel)
        if self._resid is not None:
            for pred in self._resid:
                out = out.filter(pred(out))
        self.stats.chunks += 1
        self._pending.append(out)
