"""HashJoinExec (ref: executor/join.go — build + concurrent probe workers).

TPU redesign: hash tables are scatter-hostile, so the build side becomes a
*sorted* key array (+ row payload) on device, and each probe chunk runs
one jitted kernel:

    searchsorted(build_keys, probe_keys)  -> start, count per probe row
    windowed expansion                    -> static-capacity output chunks

The only host syncs are the per-chunk match total (to pick the number of
output windows) — everything else stays on device. Duplicate build keys
are handled naturally by the [start, start+count) ranges; NULL keys never
match by masking them out of both sides.

Multi-key equi joins pack keys into one int64 using host-known ranges
(offset+stride per key); if ranges overflow int64, packing switches to
a 64-bit mixing hash of the composite key with exact on-device
verification — expanded candidate rows are filtered by real key
equality, so hash collisions only cost extra candidates, never wrong
results (the reference similarly falls back from its perfect-hash fast
path to a generic one).

Join kinds: inner, left (outer), semi, anti (with NOT IN null semantics:
any NULL build key -> empty result).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tidb_tpu.chunk.chunk import Chunk
from tidb_tpu.chunk.column import Column
from tidb_tpu.executor.base import ExecContext, Executor
from tidb_tpu.utils.dispatch import counted_jit
from tidb_tpu.utils.jitcache import cached_jit
from tidb_tpu.expression.compiler import compile_predicate, eval_expr
from tidb_tpu.types import INT64, TypeKind

__all__ = ["HashJoinExec", "IndexJoinExec"]


def _as_int64_key(d, mode: str):
    """Device-side: make a comparable int64 key (floats via bit pattern)."""
    if mode == "bits":
        return jax.lax.bitcast_convert_type(d.astype(jnp.float64), jnp.int64)
    return d.astype(jnp.int64)


# splitmix64-style mixing constants (shared finalizer lives in
# utils/hashutil; used identically on host numpy and device jnp — only
# same-function-both-sides matters, not canonicality)
from tidb_tpu.utils.hashutil import (SM_ADD as _MIX_C1, SM_MUL1 as _MIX_C2,
                                     SM_MUL2 as _MIX_C3, splitmix64)


def _hash_combine_host(key_arrays_i64):
    """uint64 mixing hash of composite int64 keys -> int64 (numpy)."""
    with np.errstate(over="ignore"):
        h = np.zeros(len(key_arrays_i64[0]), dtype=np.uint64)
        for k in key_arrays_i64:
            h = h * _MIX_C1 ^ splitmix64(k.view(np.uint64))
    return h.view(np.int64)


def _hash_combine_device(keys_i64):
    """Same mixing hash on device (jnp uint64, logical shifts)."""
    h = jnp.zeros_like(keys_i64[0], dtype=jnp.uint64)
    for k in keys_i64:
        z = jax.lax.bitcast_convert_type(k, jnp.uint64) + jnp.uint64(_MIX_C1)
        z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(_MIX_C2)
        z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(_MIX_C3)
        z = z ^ (z >> jnp.uint64(31))
        h = h * jnp.uint64(_MIX_C1) ^ z
    return jax.lax.bitcast_convert_type(h, jnp.int64)


class HashJoinExec(Executor):
    def __init__(self, schema, probe_child, build_child, kind: str,
                 probe_keys: List, build_keys: List, other_cond=None,
                 probe_schema=None, build_schema=None, exists_sem: bool = False):
        super().__init__(schema, [probe_child, build_child])
        self.kind = kind
        self.probe_keys = probe_keys
        self.build_keys = build_keys
        self.other_cond = other_cond
        self.probe_schema = probe_schema
        self.build_schema = build_schema
        self.exists_sem = exists_sem

    # ------------------------------------------------------------------

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        self.ctx = ctx
        self._pending: List[Chunk] = []
        self._drained = False
        self._build()

    def _build(self):
        """Drain the build child; compact key + payload columns to host;
        sort by key; stage back to device."""
        build_child = self.children[1]
        keys_ir = self.build_keys

        def eval_keys(chunk):
            # keyless (cross) join: a constant key matches everything
            if not keys_ir:
                z = jnp.zeros(chunk.capacity, dtype=jnp.int64)
                return [(z, jnp.ones(chunk.capacity, dtype=jnp.bool_))], chunk.sel
            outs = [eval_expr(k, chunk) for k in keys_ir]
            return outs, chunk.sel

        eval_keys = cached_jit("joinkeys", repr(keys_ir), lambda: eval_keys)

        def eval_keys_any(chunk):
            # numpy first: key exprs are almost always column refs /
            # dict lookups, and the jitted evaluator recompiles per
            # query (per-query uids in its closure)
            if not keys_ir:
                z = np.zeros(chunk.capacity, dtype=np.int64)
                return ([(z, np.ones(chunk.capacity, dtype=np.bool_))],
                        chunk.sel)
            outs = [self._np_eval_key(k, chunk) for k in keys_ir]
            if all(o is not None for o in outs):
                return outs, chunk.sel
            return eval_keys(chunk)

        key_cols = [[] for _ in (keys_ir or [None])]
        key_ok = []
        payload: dict = {c.uid: ([], []) for c in (self.build_schema or [])}
        for chunk in build_child.chunks():
            outs, sel = eval_keys_any(chunk)
            sel = np.asarray(sel)
            live = np.nonzero(sel)[0]
            ok = np.ones(len(live), dtype=np.bool_)
            for i, (d, v) in enumerate(outs):
                key_cols[i].append(np.asarray(d)[live])
                ok &= np.asarray(v)[live]
            key_ok.append(ok)
            for uid in payload:
                col = chunk.columns[uid]
                payload[uid][0].append(np.asarray(col.data)[live])
                payload[uid][1].append(np.asarray(col.valid)[live])

        key_arrays = [np.concatenate(p) if p else np.zeros(0, dtype=np.int64) for p in key_cols]
        ok = np.concatenate(key_ok) if key_ok else np.zeros(0, dtype=np.bool_)
        self._build_had_null = bool((~ok).any())
        # NULL keys can never match: drop them from the build side
        key_arrays = [k[ok] for k in key_arrays]

        packed, self._pack_info = self._pack_keys_host(key_arrays)
        order = np.argsort(packed, kind="stable")
        self._n_build = len(packed)
        keep_np = self._host_probe_eligible()
        self._sorted_keys_np = packed[order] if keep_np else None
        self._sorted_keys = jnp.asarray(packed[order])
        if self._hash_mode:
            # raw per-column key values, build-sorted, for exact
            # verification of hash-expanded candidate rows on device
            self._build_keyvals_sorted = [
                jnp.asarray(k[order]) for k in self._build_keyvals
            ]
        self._build_payload = {}
        self._build_payload_np = {}
        nbytes = packed.nbytes
        for uid, (dlist, vlist) in payload.items():
            d = np.concatenate(dlist) if dlist else np.zeros(0)
            v = np.concatenate(vlist) if vlist else np.zeros(0, dtype=np.bool_)
            d, v = d[ok][order], v[ok][order]
            nbytes += d.nbytes + v.nbytes
            if keep_np:
                self._build_payload_np[uid] = (d, v)
            self._build_payload[uid] = (jnp.asarray(d), jnp.asarray(v))
        # account the materialized build side against the query budget
        # (ref: HashJoinExec's build RowContainer under the memory tracker)
        self._mem_tracker = self.ctx.mem_tracker.child("hashjoin.build")
        self._build_bytes = int(nbytes)
        self._mem_tracker.consume(self._build_bytes)
        self._probe_fn = None

    def close(self) -> None:
        if getattr(self, "_build_bytes", 0):
            self._mem_tracker.release(self._build_bytes)
            self._build_bytes = 0
        super().close()

    def _pack_keys_host(self, key_arrays: List[np.ndarray]):
        """Combine multi-keys into one int64 via range packing. Returns
        (packed, info) where info lets the probe side apply the same
        transform. If the range product overflows int64, switch to a
        64-bit mixing hash with exact device-side verification (see
        module docstring) — sets self._hash_mode."""
        self._hash_mode = False
        if len(key_arrays) == 1:
            k = key_arrays[0]
            if np.issubdtype(k.dtype, np.floating):
                return k.astype(np.float64).view(np.int64), [("bits", 0, 1, 0)]
            return k.astype(np.int64), [("int", 0, 1, 0)]
        conv, modes = [], []
        for k in key_arrays:
            if np.issubdtype(k.dtype, np.floating):
                conv.append(k.astype(np.float64).view(np.int64))
                modes.append("bits")
            else:
                conv.append(k.astype(np.int64))
                modes.append("int")
        info = []
        packed = np.zeros(len(key_arrays[0]), dtype=np.int64)
        stride = 1
        for k, mode in zip(conv, modes):
            lo = int(k.min()) if len(k) else 0
            hi = int(k.max()) if len(k) else 0
            rng = hi - lo + 1
            if rng <= 0 or rng * stride > (1 << 62):
                self._hash_mode = True
                self._build_keyvals = conv
                return _hash_combine_host(conv), [("hash", modes)]
            info.append((mode, lo, stride, rng))
            packed = packed + (k - lo) * stride
            stride *= rng
        return packed, info

    def _pack_probe(self, outs):
        """Device-side packing of probe keys with the build-side info.
        Returns (packed int64, ok mask) — keys outside the build range get
        ok=False (they cannot match)."""
        info = self._pack_info
        if len(outs) == 1:
            d, v = outs[0]
            ones = jnp.ones_like(v)
            return _as_int64_key(d, info[0][0]), v, ones
        if info[0][0] == "hash":
            modes = info[0][1]
            valid = jnp.ones_like(outs[0][1])
            keys = []
            for (d, v), mode in zip(outs, modes):
                keys.append(_as_int64_key(d, mode))
                valid = valid & v
            # all hashes are "in range"; false candidates are removed by
            # the exact verification filter after expansion
            return _hash_combine_device(keys), valid, jnp.ones_like(valid)
        packed = jnp.zeros_like(outs[0][0], dtype=jnp.int64)
        valid = jnp.ones_like(outs[0][1])
        in_range = jnp.ones_like(outs[0][1])
        for (d, v), (mode, lo, stride, rng) in zip(outs, info):
            d = _as_int64_key(d, mode)
            valid = valid & v
            # probe keys outside the build range can't match; without this
            # mask they'd alias into other (lo, stride) cells and collide.
            # kept separate from `valid`: an out-of-range key is a definite
            # non-match (anti joins must keep the row), not a NULL.
            in_range = in_range & (d >= lo) & (d < lo + rng)
            packed = packed + jnp.clip(d - lo, 0, max(rng - 1, 0)) * stride
        return packed, valid, in_range

    # ------------------------------------------------------------------

    def _make_probe_fn(self):
        keys_ir = self.probe_keys
        sorted_keys = self._sorted_keys

        def probe(chunk):
            if not keys_ir:
                packed = jnp.zeros(chunk.capacity, dtype=jnp.int64)
                valid = in_range = jnp.ones(chunk.capacity, dtype=jnp.bool_)
            else:
                outs = [eval_expr(k, chunk) for k in keys_ir]
                packed, valid, in_range = self._pack_probe(outs)
            ok = valid & chunk.sel
            start = jnp.searchsorted(sorted_keys, packed, side="left")
            end = jnp.searchsorted(sorted_keys, packed, side="right")
            count = jnp.where(ok & in_range, end - start, 0)
            return start, count, ok

        return counted_jit(probe)

    def next(self) -> Optional[Chunk]:
        while True:
            if self._pending:
                return self._pending.pop(0)
            if self._drained:
                return None
            chunk = self.children[0].next()
            if chunk is None:
                self._drained = True
                continue
            self._process_probe_chunk(chunk)

    def _host_probe_eligible(self) -> bool:
        """The numpy probe path covers the workhorse shapes on the host
        engine (ctx.device_agg off): sorted-array binary search + exact
        np.repeat expansion beat the jitted XLA:CPU searchsorted + padded
        window gathers ~3x. Left joins and filtered/hash-verified probes
        keep the jitted path (NULL padding + re-verification logic)."""
        return (not getattr(self.ctx, "device_agg", True)
                and self.kind in ("inner", "semi", "anti")
                and self.other_cond is None
                and not self._hash_mode)

    @staticmethod
    def _keep_unmatched(sel, ok, matched, build_had_null, exists_sem):
        """Anti-join keep mask, shared (semantically) with the jitted
        path: NOT EXISTS keeps NULL-key probe rows; NOT IN goes empty
        when the build side held a NULL key (caller handles that)."""
        if exists_sem:
            return sel & ~(ok & matched)
        return sel & ok & ~matched

    def _np_eval_key(self, e, chunk: Chunk):
        """Numpy (data, valid) for the key shapes the host path meets —
        column refs, literals, dictionary Lookups. Returns None for
        anything else (caller falls back to the jitted evaluator).
        Evaluating keys without jax matters: a per-join jax.jit keyed on
        per-query uids recompiled EVERY query (~20ms per join — the
        fixed cost that made every small host join cost ~30ms)."""
        from tidb_tpu.expression.expr import ColumnRef, Literal, Lookup

        if isinstance(e, ColumnRef):
            col = chunk.columns[e.name]
            return np.asarray(col.data), np.asarray(col.valid)
        if isinstance(e, Literal):
            cap = chunk.capacity
            dt = e.type_.np_dtype  # match the jitted evaluator's dtype:
            # pack-mode selection ('bits' for floats) depends on it
            if e.value is None:
                return (np.zeros(cap, dtype=dt),
                        np.zeros(cap, dtype=np.bool_))
            return (np.full(cap, e.value, dtype=dt),
                    np.ones(cap, dtype=np.bool_))
        if isinstance(e, Lookup):
            base = self._np_eval_key(e.arg, chunk)
            if base is None:
                return None
            data, valid = base
            table = np.asarray(e.table, dtype=e.type_.np_dtype)
            if len(table) == 0:  # empty dictionary: every code is absent
                return (np.zeros(len(data), dtype=e.type_.np_dtype),
                        np.zeros(len(data), dtype=np.bool_))
            idx = np.clip(data.astype(np.int64), 0, len(e.table) - 1)
            out = table[idx]
            if e.table_valid is not None:
                tv = np.asarray(e.table_valid, dtype=np.bool_)
                valid = valid & tv[idx]
            valid = valid & (data >= 0) & (data < len(e.table))
            return out, valid
        return None

    @staticmethod
    def _np_as_int64(d: np.ndarray, mode: str) -> np.ndarray:
        if mode == "bits":
            return d.astype(np.float64).view(np.int64)
        return d.astype(np.int64)

    def _np_pack_probe(self, outs):
        """Numpy mirror of _pack_probe (range packing; hash mode never
        reaches the numpy path — _host_probe_eligible excludes it)."""
        info = self._pack_info
        if len(outs) == 1:
            d, v = outs[0]
            return (self._np_as_int64(d, info[0][0]), v,
                    np.ones_like(v, dtype=np.bool_))
        packed = np.zeros(len(outs[0][0]), dtype=np.int64)
        valid = np.ones(len(outs[0][0]), dtype=np.bool_)
        in_range = np.ones_like(valid)
        for (d, v), (mode, lo, stride, rng) in zip(outs, info):
            d = self._np_as_int64(d, mode)
            valid = valid & v
            in_range = in_range & (d >= lo) & (d < lo + rng)
            packed = packed + np.clip(d - lo, 0, max(rng - 1, 0)) * stride
        return packed, valid, in_range

    def _np_probe_keys(self, chunk: Chunk):
        """Key eval + pack for the numpy probe: pure numpy when the key
        exprs allow it, else a jitted fallback (one fn per join)."""
        mode = getattr(self, "_np_key_mode", None)
        if mode != "jit":
            outs = [self._np_eval_key(k, chunk) for k in self.probe_keys]
            if self.probe_keys and all(o is not None for o in outs):
                self._np_key_mode = "np"
                packed, valid, in_r = self._np_pack_probe(outs)
                return packed, valid & np.asarray(chunk.sel), in_r
            self._np_key_mode = "jit"
        if getattr(self, "_np_key_fn", None) is None:
            keys_ir = self.probe_keys

            def keyfn(ch):
                if not keys_ir:
                    ones = jnp.ones(ch.capacity, dtype=jnp.bool_)
                    return (jnp.zeros(ch.capacity, dtype=jnp.int64),
                            ones, ones)
                outs = [eval_expr(k, ch) for k in keys_ir]
                return self._pack_probe(outs)

            self._np_key_fn = counted_jit(keyfn)
        packed, valid, in_range = self._np_key_fn(chunk)
        return (np.asarray(packed), np.asarray(valid) & np.asarray(chunk.sel),
                np.asarray(in_range))

    def _process_probe_chunk_np(self, chunk: Chunk):
        packed, ok, in_r = self._np_probe_keys(chunk)
        sk = self._sorted_keys_np
        start = np.searchsorted(sk, packed, side="left")
        end = np.searchsorted(sk, packed, side="right")
        count = np.where(ok & in_r, end - start, 0)

        if self.kind in ("semi", "anti"):
            matched = count > 0
            if self.kind == "semi":
                self._pending.append(chunk.with_sel(jnp.asarray(ok & matched)))
                return
            if self._build_had_null and not self.exists_sem:
                return  # NOT IN with NULL in subquery: no row is ever TRUE
            keep = self._keep_unmatched(np.asarray(chunk.sel), ok, matched,
                                        self._build_had_null, self.exists_sem)
            self._pending.append(chunk.with_sel(jnp.asarray(keep)))
            return

        total = int(count.sum())
        if total == 0:
            return
        cum = np.cumsum(count)
        cum_excl = cum - count
        cap = self.ctx.chunk_capacity
        build_schema = {c.uid: c for c in (self.build_schema or [])}
        probe_np = {uid: (np.asarray(col.data), np.asarray(col.valid))
                    for uid, col in chunk.columns.items()}
        types = {uid: chunk.columns[uid].type_ for uid in probe_np}
        types.update({uid: build_schema[uid].type_
                      for uid in self._build_payload_np})
        # window the EXPANSION itself (not just the emission): a
        # many-to-many join's full expansion can dwarf host memory
        rows_of_window = np.searchsorted(cum, np.arange(0, total, cap),
                                         side="right")
        for wi, w in enumerate(range(0, total, cap)):
            hi = min(w + cap, total)
            m = hi - w
            lo_row = rows_of_window[wi]
            hi_row = int(np.searchsorted(cum, hi - 1, side="right"))
            rows = np.arange(lo_row, hi_row + 1)
            reps = np.minimum(cum[rows], hi) - np.maximum(cum_excl[rows], w)
            probe_row = np.repeat(rows, reps)
            k = np.arange(w, hi, dtype=np.int64) - cum_excl[probe_row]
            build_pos = start[probe_row] + k
            arrays, valids = {}, {}
            for uid, (d, v) in probe_np.items():
                arrays[uid] = d[probe_row]
                valids[uid] = v[probe_row]
            for uid, (d, v) in self._build_payload_np.items():
                arrays[uid] = d[build_pos]
                valids[uid] = v[build_pos]
            ccap = 8
            while ccap < m:
                ccap *= 2
            self._pending.append(
                Chunk.from_numpy(arrays, types, valids=valids, capacity=ccap))

    def _process_probe_chunk(self, chunk: Chunk):
        if self._host_probe_eligible():
            self._process_probe_chunk_np(chunk)
            return
        if self._probe_fn is None:
            self._probe_fn = self._make_probe_fn()
            self._expand_fn = self._make_expand_fn()
            self._filter_fns = {}
        start, count, ok = self._probe_fn(chunk)
        # hash-packed keys need exact re-verification of every candidate
        # row, so they take the same filtered paths as other_cond
        has_filter = self.other_cond is not None or self._hash_mode

        if self.kind in ("semi", "anti"):
            if not has_filter:
                matched = count > 0
            else:
                matched = self._qualified_matches(chunk, start, count)
            if self.kind == "semi":
                self._pending.append(chunk.with_sel(ok & matched))
                return
            if self._build_had_null and not self.exists_sem:
                return  # NOT IN with NULL in subquery: no row is ever TRUE
            if self.exists_sem:
                # NOT EXISTS: a NULL probe key never matches -> row kept
                keep = chunk.sel & ~(ok & matched)
            else:
                keep = chunk.sel & ok & ~matched
            self._pending.append(chunk.with_sel(keep))
            return

        real_count = count
        left_other = self.kind == "left" and has_filter
        if self.kind == "left" and not left_other:
            count = jnp.where(chunk.sel, jnp.maximum(count, 1), 0)

        cum = jnp.cumsum(count)
        total = int(cum[-1])
        cap = self.ctx.chunk_capacity
        matched = np.zeros(chunk.capacity, dtype=np.bool_) if left_other else None
        for w in range(0, total, cap):
            out = self._expand_fn(chunk, start, count, real_count, cum, jnp.int64(w))
            if has_filter:
                out = self._match_filter(out)
                if left_other:
                    sel = np.asarray(out.sel)
                    rows = np.asarray(out.columns["__probe_row__"].data)[sel]
                    matched[rows] = True
                # bookkeeping columns stay internal to the match tracking
                out = Chunk(
                    {u: c for u, c in out.columns.items()
                     if u not in ("__probe_row__", "__build_pos__")},
                    out.sel,
                )
            self._pending.append(out)
        if left_other:
            # probe rows whose every match failed other_cond (or that had
            # none) emit one NULL-payload row each, per LEFT JOIN semantics
            unmatched = chunk.sel & jnp.asarray(~matched)
            if bool(np.asarray(unmatched).any()):
                self._pending.append(self._null_build_chunk(chunk, unmatched))

    def _qualified_matches(self, chunk: Chunk, start, count):
        """[capacity] bool: probe rows with at least one build match passing
        other_cond — via windowed expansion (semi/anti joins carrying extra
        conditions, e.g. decorrelated EXISTS with non-equi predicates)."""
        cum = jnp.cumsum(count)
        total = int(cum[-1])
        matched = np.zeros(chunk.capacity, dtype=np.bool_)
        cap = self.ctx.chunk_capacity
        for w in range(0, total, cap):
            out = self._expand_fn(chunk, start, count, count, cum, jnp.int64(w))
            out = self._match_filter(out)
            sel = np.asarray(out.sel)
            rows = np.asarray(out.columns["__probe_row__"].data)[sel]
            matched[rows] = True
        return jnp.asarray(matched)

    def _match_filter(self, out: Chunk) -> Chunk:
        """Filter expanded candidate rows: exact key equality when the
        keys were hash-packed, then other_cond if present."""
        if "mf" not in self._filter_fns:
            other = compile_predicate(self.other_cond) if self.other_cond is not None else None
            hash_mode = self._hash_mode
            probe_keys = self.probe_keys
            modes = self._pack_info[0][1] if hash_mode else ()
            keyvals = getattr(self, "_build_keyvals_sorted", ())

            def fn(ch):
                keep = ch.sel
                if hash_mode:
                    pos = ch.columns["__build_pos__"].data
                    for k_ir, mode, bv in zip(probe_keys, modes, keyvals):
                        pv = _as_int64_key(eval_expr(k_ir, ch)[0], mode)
                        keep = keep & (jnp.take(bv, pos, mode="clip") == pv)
                if other is not None:
                    keep = keep & other(ch)
                return ch.with_sel(keep)

            self._filter_fns["mf"] = counted_jit(fn)
        return self._filter_fns["mf"](out)

    def _null_build_chunk(self, chunk: Chunk, sel) -> Chunk:
        """Probe columns pass through; build payload is all-NULL."""
        build_schema = {c.uid: c for c in (self.build_schema or [])}
        cols = dict(chunk.columns)
        for uid in self._build_payload:
            c = build_schema[uid]
            cols[uid] = Column(
                np.zeros(chunk.capacity, dtype=c.type_.np_dtype),
                np.zeros(chunk.capacity, dtype=np.bool_),
                c.type_,
            )
        return Chunk(cols, sel)

    def _make_expand_fn(self):
        payload = self._build_payload
        build_schema = {c.uid: c for c in (self.build_schema or [])}
        kind = self.kind
        n_build = max(self._n_build, 1)
        cap = self.ctx.chunk_capacity
        # only the match-filter path reads the bookkeeping columns;
        # don't make the hot inner-join path carry them
        with_probe_row = self.other_cond is not None or self._hash_mode
        with_build_pos = self._hash_mode

        def expand(chunk, start, count, real_count, cum, w):
            j = jnp.arange(cap, dtype=jnp.int64) + w
            total = cum[-1]
            valid_out = j < total
            probe_row = jnp.searchsorted(cum, j, side="right")
            probe_row = jnp.clip(probe_row, 0, chunk.capacity - 1)
            cum_excl = cum[probe_row] - count[probe_row]
            k = j - cum_excl
            build_pos = jnp.clip(start[probe_row] + k, 0, n_build - 1)

            cols = {}
            for uid, col in chunk.columns.items():
                cols[uid] = col.gather(probe_row, valid_out)
            if with_probe_row:
                cols["__probe_row__"] = Column(probe_row, valid_out, INT64)
            if with_build_pos:
                cols["__build_pos__"] = Column(build_pos, valid_out, INT64)
            # left join emits one slot even for unmatched probe rows; the
            # build payload is NULL there (k beyond the real match count)
            real = k < real_count[probe_row]
            for uid, (d, v) in payload.items():
                data = jnp.take(d, build_pos, mode="clip")
                valid = jnp.take(v, build_pos, mode="clip") & valid_out
                if kind == "left":
                    valid = valid & real
                c = build_schema[uid]
                cols[uid] = Column(data, valid, c.type_)
            return Chunk(cols, valid_out)

        return counted_jit(expand)


class IndexJoinExec(Executor):
    """Index-lookup join (ref: executor's IndexLookUpJoin; SURVEY.md:91):
    the inner side is never scanned — each outer chunk's join keys are
    batch-binary-searched against the inner table's sorted index cache
    (the same substrate PointGet/IndexRangeScan probe), candidate rows
    pass MVCC visibility, and matches gather straight from table
    storage. O((outer + matches) log n) host work, independent of the
    inner table's size — the access-path alternative the cascades memo
    costs against the hash join's exchange + build."""

    def __init__(self, schema, outer: Executor, eq_outer, inner_table,
                 index_name, inner_schema, inner_cond, other_cond):
        super().__init__(schema, [outer])
        self.eq_outer = eq_outer
        self.inner_table = inner_table
        self.index_name = index_name
        self.inner_schema = inner_schema
        self.inner_cond = inner_cond
        self.other_cond = other_cond

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        self.ctx = ctx
        from tidb_tpu.expression.compiler import compile_expr

        self._key_fns = [compile_expr(e) for e in self.eq_outer]
        self._pending: List[Chunk] = []
        self._skeys, self._srows = self.inner_table._sorted_index(
            self.index_name)
        self._resid = None
        if self.inner_cond is not None or self.other_cond is not None:
            conds = [c for c in (self.inner_cond, self.other_cond)
                     if c is not None]
            self._resid = [compile_predicate(c) for c in conds]

    def next(self) -> Optional[Chunk]:
        while True:
            if self._pending:
                return self._pending.pop(0)
            ch = self.children[0].next()
            if ch is None:
                return None
            self._join_chunk(ch)

    def _join_chunk(self, ch: Chunk) -> None:
        sel = np.asarray(ch.sel)
        live = np.nonzero(sel)[0]
        if len(live) == 0:
            return
        skeys, srows = self._skeys, self._srows
        nkeys = len(self._key_fns)
        i64 = np.iinfo(np.int64)
        # the index may be wider than the join key set (a composite pk
        # probed on its prefix): floor/ceil the suffix fields so the
        # whole equal-prefix run matches, not just suffix == 0
        probe_lo = np.zeros(len(live), dtype=skeys.dtype)
        probe_hi = np.zeros(len(live), dtype=skeys.dtype)
        for name in skeys.dtype.names[nkeys:]:
            probe_lo[name] = i64.min
            probe_hi[name] = i64.max
        kvalid = np.ones(len(live), dtype=np.bool_)
        for i, fn in enumerate(self._key_fns):
            col = fn(ch)
            kvalid &= np.asarray(col.valid)[live]
            keys = np.asarray(col.data)[live].astype(np.int64)
            probe_lo[f"k{i}"] = keys
            probe_hi[f"k{i}"] = keys
        # NULL keys match nothing; searchsorted over the composite tuple
        # gives the exact equality run — no hashing, no collisions
        lo = np.searchsorted(skeys, probe_lo, side="left")
        hi = np.searchsorted(skeys, probe_hi, side="right")
        counts = np.where(kvalid, hi - lo, 0)
        total = int(counts.sum())
        if total == 0:
            return
        outer_pos = np.repeat(np.arange(len(live)), counts)
        starts = np.repeat(lo, counts)
        offs = np.arange(total) - np.repeat(
            np.cumsum(counts) - counts, counts)
        cand = srows[starts + offs]
        vis = self.inner_table._mvcc_mask(
            cand, read_ts=self.ctx.read_ts, marker=self.ctx.txn_marker)
        cand = cand[vis]
        outer_rows = live[outer_pos[vis]]
        # windowed emission: expansion is bounded to chunk_capacity per
        # output chunk (the HashJoinExec contract), so a many-match key
        # set cannot spike host memory or mint giant downstream shapes
        win = max(self.ctx.chunk_capacity, 8)
        for s0 in range(0, len(cand), win):
            self._emit(ch, outer_rows[s0:s0 + win], cand[s0:s0 + win])

    def _emit(self, ch: Chunk, outer_rows, cand) -> None:
        if len(cand) == 0:
            return
        cap = 8
        while cap < len(cand):
            cap *= 2
        cols = {}
        for c in self.inner_schema:
            d = self.inner_table.data[c.name][cand]
            v = self.inner_table.valid[c.name][cand]
            cols[c.uid] = Column.from_numpy(d, c.type_, valid=v,
                                            capacity=cap)
        for uid, col in ch.columns.items():
            d = np.asarray(col.data)[outer_rows]
            v = np.asarray(col.valid)[outer_rows]
            cols[uid] = Column.from_numpy(d, col.type_, valid=v,
                                          capacity=cap)
        osel = np.zeros(cap, dtype=np.bool_)
        osel[: len(cand)] = True
        out = Chunk(cols, osel)
        if self._resid is not None:
            for pred in self._resid:
                out = out.filter(pred(out))
        self.stats.chunks += 1
        self._pending.append(out)
