"""executorBuilder (ref: executor/builder.go): physical plan -> executors.

The builder performs pipeline fusion: chains of Selection/Projection above
a TableFullScan collapse into the scan's jitted fragment (stages), so a
scan+filter+project runs as ONE device dispatch per chunk — the shape the
coprocessor gives the reference for free.
"""

from __future__ import annotations

from typing import List

from tidb_tpu.errors import PlanError
from tidb_tpu.executor.aggregate import HashAggExec
from tidb_tpu.executor.base import Executor
from tidb_tpu.executor.join import HashJoinExec, IndexJoinExec
from tidb_tpu.executor.scan import ProjectionExec, SelectionExec, TableScanExec
from tidb_tpu.executor.sort import LimitExec, SortExec, TopNExec, UnionExec
from tidb_tpu.planner.physical import (
    PHashAgg,
    PHashJoin,
    PIndexJoin,
    PIndexRangeScan,
    PPartitionScan,
    PLimit,
    PProjection,
    PPointGet,
    PScan,
    PSelection,
    PSort,
    PTopN,
    PUnion,
    PWindow,
    PhysicalPlan,
)

__all__ = ["build_executor", "peel_stages", "scan_stages_for"]


def peel_stages(plan: PhysicalPlan):
    """Strip the fusible Selection/Projection chain off `plan`.

    Returns (stages, base): stages in execution order, base the first
    non-fusible node. Shared by the single-chip fusion below and the
    distributed fragment matcher (parallel/executor.py) so both tiers
    always fuse the same plan shapes."""
    rev, base = [], plan
    while True:
        if isinstance(base, PSelection):
            rev.append(("filter", base.cond))
            base = base.child
        elif isinstance(base, PProjection):
            rev.append(("project", list(zip([c.uid for c in base.schema], base.exprs))))
            base = base.child
        else:
            break
    return list(reversed(rev)), base


def scan_stages_for(scan: PScan, stages) -> list:
    """Prepend the scan's pushed filter to a fused stage list."""
    out = []
    if scan.pushed_cond is not None:
        out.append(("filter", scan.pushed_cond))
    out.extend(stages)
    return out


def scan_prune_bounds(scan: PScan):
    """Zone-consultable bounds from the scan's pushed filter (ISSUE 8):
    the columnar segment store prunes whole segments against these
    before any host→device staging. Computed here — at executor-build
    time — so a plan-cache hit with freshly patched literal slots
    always re-derives bounds from the CURRENT literals."""
    if scan.pushed_cond is None or scan.table is None:
        return ()
    from tidb_tpu.columnar.zonemap import collect_prune_bounds

    uid_map = {c.uid: (c.name, c.type_) for c in scan.schema}
    return collect_prune_bounds(scan.pushed_cond, uid_map)


def _try_fused_scan_agg(plan: PHashAgg):
    """HashAgg whose child peels to a PLAIN table scan pipeline runs as
    one fused scan→filter→project→partial-agg fragment (ISSUE 9): one
    jitted program per chunk, device-resident state, one fetch at
    finalize. Point/range/partition access paths keep the classic tree
    (their row sets come from literal-keyed host probes), as does
    anything the context later rules out — FusedScanAggExec falls back
    through `fallback_build` at open() in that case, so the routing
    decision needing ExecContext state doesn't have to happen here."""
    from tidb_tpu.executor.pipeline import FusedScanAggExec

    stages, base = peel_stages(plan.child)
    if type(base) is not PScan or base.table is None:
        return None
    if plan.strategy != "segment":
        # plan-STATIC generic-strategy gates decide here so permanently
        # unfusible shapes (DISTINCT, non-core funcs, global generic)
        # keep the classic tree — and its per-operator EXPLAIN ANALYZE
        # breakdown. Only ctx-dependent gates (sysvars, device_agg)
        # defer to the open()-time delegate.
        from tidb_tpu.planner.logical import core_generic_agg

        if not core_generic_agg(plan.group_exprs, plan.aggs):
            return None

    def fallback(plan=plan):
        return HashAggExec(
            plan.schema, build_executor(plan.child), plan.group_exprs,
            plan.group_uids, plan.aggs, plan.strategy,
            segment_sizes=getattr(plan, "segment_sizes", None))

    return FusedScanAggExec(
        plan.schema, base.schema, base.table,
        scan_stages_for(base, stages), scan_prune_bounds(base),
        plan.group_exprs, plan.group_uids, plan.aggs, plan.strategy,
        segment_sizes=getattr(plan, "segment_sizes", None),
        fallback_build=fallback)


def _build_hash_join(plan: PHashJoin) -> HashJoinExec:
    """The classic pull-based hash-join tree (also the fused path's
    open()-time fallback delegate)."""
    probe_idx = 1 - plan.build_side
    probe_plan = plan.children[probe_idx]
    build_plan = plan.children[plan.build_side]
    probe_keys = plan.eq_left if probe_idx == 0 else plan.eq_right
    build_keys = plan.eq_right if plan.build_side == 1 else plan.eq_left
    # semi/anti joins need no build payload — unless an other_cond must
    # evaluate build columns during the probe, and then only those
    if plan.kind in ("semi", "anti"):
        if plan.other_cond is None:
            build_payload_schema = []
        else:
            from tidb_tpu.expression.expr import ColumnRef, walk

            refs = {n.name for n in walk(plan.other_cond)
                    if isinstance(n, ColumnRef)}
            build_payload_schema = [c for c in build_plan.schema
                                    if c.uid in refs]
    else:
        build_payload_schema = list(build_plan.schema)
    return HashJoinExec(
        plan.schema,
        build_executor(probe_plan),
        build_executor(build_plan),
        plan.kind,
        probe_keys,
        build_keys,
        other_cond=plan.other_cond,
        probe_schema=list(probe_plan.schema),
        build_schema=build_payload_schema,
        exists_sem=plan.exists_sem,
    )


def _try_fused_scan_probe(plan: PHashJoin):
    """Inner or LEFT OUTER hash join whose probe side peels to a PLAIN
    table scan pipeline runs as a fused scan→probe fragment (ISSUE 10,
    widened by ISSUE 18 to composite keys and the left-outer pad): one
    jitted decode+filter+project+probe+expand program per staged probe
    chunk, the build side device-resident (and device-buffer-cached
    when it is itself a plain scan over a stored table). Plan-STATIC
    gates decide here — semi/anti kinds and other_cond keep the classic
    tree with its per-operator EXPLAIN ANALYZE breakdown; ctx-dependent
    gates (sysvars, device-engine routing) defer to the open()-time
    delegate, as does the data-dependent hash-mode packing escape
    (composite key ranges overflowing int64 need the classic probe's
    exact re-verification, known only after the build drain)."""
    from tidb_tpu.executor.pipeline import FusedScanProbeExec

    if plan.kind not in ("inner", "left") or plan.other_cond is not None:
        return None
    if plan.exists_sem:
        return None
    if plan.kind == "left" and plan.build_side != 1:
        # the fused probe streams the PRESERVED side; a left join built
        # on the left would pad the wrong side
        return None
    probe_idx = 1 - plan.build_side
    probe_plan = plan.children[probe_idx]
    build_plan = plan.children[plan.build_side]
    probe_keys = plan.eq_left if probe_idx == 0 else plan.eq_right
    build_keys = plan.eq_right if plan.build_side == 1 else plan.eq_left
    if len(probe_keys) != len(build_keys) or not probe_keys:
        return None
    stages, base = peel_stages(probe_plan)
    if type(base) is not PScan or base.table is None:
        return None
    # build-side cache eligibility: only a plain scan pipeline over a
    # stored table proves a parked build current via table_ident; the
    # tag carries the peeled plan's full shape (incl. literal values —
    # a plan-cache hit patches literals before the builder runs)
    bstages, bbase = peel_stages(build_plan)
    build_table = bbase.table if type(bbase) is PScan else None
    build_tag = None
    if build_table is not None:
        build_tag = repr((bstages, getattr(bbase, "pushed_cond", None),
                          build_keys,
                          tuple(c.uid for c in build_plan.schema)))

    def fallback(plan=plan):
        return _build_hash_join(plan)

    return FusedScanProbeExec(
        plan.schema, base.schema, base.table,
        scan_stages_for(base, stages), scan_prune_bounds(base),
        list(probe_plan.schema), probe_keys, build_keys,
        list(build_plan.schema),
        build_child_build=lambda: build_executor(build_plan),
        build_table=build_table, build_tag=build_tag,
        kind=plan.kind, fallback_build=fallback)


def _try_fused_scan_topn(plan):
    """ORDER BY [+ LIMIT] root whose child peels to a PLAIN table scan
    pipeline runs as a fused scan→top-k fragment (ISSUE 18): one jitted
    decode+filter+project+top-k-merge program per staged chunk, a
    bounded device state of the current winners, one fetch at finalize.
    Plan-static gates only reject shapes with no scan pipeline to fuse;
    the capacity gates (LIMIT + offset vs the chunk capacity, table
    size for a full ORDER BY) are ctx/data-dependent and defer to the
    open()-time delegate — which is where the k-overflow feedback
    record comes from."""
    from tidb_tpu.executor.pipeline import FusedScanTopNExec

    if not plan.items:
        return None
    stages, base = peel_stages(plan.child)
    if type(base) is not PScan or base.table is None:
        return None
    topn = isinstance(plan, PTopN)

    def fallback(plan=plan):
        if isinstance(plan, PTopN):
            return TopNExec(plan.schema, build_executor(plan.child),
                            plan.items, plan.count, plan.offset)
        return SortExec(plan.schema, build_executor(plan.child),
                        plan.items)

    return FusedScanTopNExec(
        plan.schema, base.schema, base.table,
        scan_stages_for(base, stages), scan_prune_bounds(base),
        plan.items, plan.count if topn else None,
        plan.offset if topn else 0, full_sort=not topn,
        fallback_build=fallback)


def build_executor(plan: PhysicalPlan) -> Executor:
    """Build the executor for `plan` and annotate it with the plan node
    it answers for: plan feedback (ISSUE 15) and EXPLAIN ANALYZE's
    est/drift columns pair each executor's actual row count with its
    node's est_rows through this link. Fused/peeled executors carry the
    TOP of the chain they absorbed — their output is that node's."""
    e = _build_executor(plan)
    e._feedback_plan = plan
    return e


def _build_executor(plan: PhysicalPlan) -> Executor:
    # pipeline fusion: Selection/Projection chains over a scan
    stages, base = peel_stages(plan)
    if isinstance(base, PPointGet):
        from tidb_tpu.executor.scan import PointGetExec

        return PointGetExec(
            schema=base.schema,
            table=base.table,
            # a key-covered filter is subsumed by the unique-index probe
            # itself; only this single-chip point path may skip it — the
            # dist tier treats PPointGet as a plain scan and still needs
            # the pushed filter
            stages=(stages if base.cond_covered
                    else scan_stages_for(base, stages)),
            index_name=base.index_name,
            key_values=base.key_values,
            out_schema=plan.schema,
        )
    if isinstance(base, PIndexRangeScan):
        from tidb_tpu.executor.scan import IndexRangeScanExec

        return IndexRangeScanExec(
            schema=base.schema,
            table=base.table,
            stages=scan_stages_for(base, stages),
            index_name=base.index_name,
            eq_values=base.eq_values,
            range_lo=base.range_lo,
            range_hi=base.range_hi,
            lo_incl=base.lo_incl,
            hi_incl=base.hi_incl,
            out_schema=plan.schema,
        )
    if isinstance(base, PPartitionScan):
        from tidb_tpu.executor.scan import PartitionScanExec

        return PartitionScanExec(
            schema=base.schema,
            table=base.table,
            stages=scan_stages_for(base, stages),
            part_ids=base.part_ids,
            out_schema=plan.schema,
        )
    if isinstance(base, PScan):
        return TableScanExec(
            schema=base.schema,
            table=base.table,
            stages=scan_stages_for(base, stages),
            out_schema=plan.schema,
            prune_bounds=scan_prune_bounds(base),
        )

    if isinstance(plan, PSelection):
        return SelectionExec(plan.schema, build_executor(plan.child), plan.cond)
    if isinstance(plan, PProjection):
        return ProjectionExec(plan.schema, build_executor(plan.child), plan.exprs)
    if isinstance(plan, PScan):
        scan_stages = []
        if plan.pushed_cond is not None:
            scan_stages.append(("filter", plan.pushed_cond))
        return TableScanExec(schema=plan.schema, table=plan.table,
                             stages=scan_stages,
                             prune_bounds=scan_prune_bounds(plan))
    if isinstance(plan, PHashAgg):
        fused = _try_fused_scan_agg(plan)
        if fused is not None:
            return fused
        return HashAggExec(
            plan.schema,
            build_executor(plan.child),
            plan.group_exprs,
            plan.group_uids,
            plan.aggs,
            plan.strategy,
            segment_sizes=getattr(plan, "segment_sizes", None),
        )
    if isinstance(plan, PIndexJoin):
        return IndexJoinExec(
            plan.schema,
            build_executor(plan.child),
            plan.eq_outer,
            plan.inner_table,
            plan.index_name,
            plan.inner_schema,
            plan.inner_cond,
            plan.other_cond,
        )
    if isinstance(plan, PHashJoin):
        fused = _try_fused_scan_probe(plan)
        if fused is not None:
            return fused
        return _build_hash_join(plan)
    if isinstance(plan, PSort):
        fused = _try_fused_scan_topn(plan)
        if fused is not None:
            return fused
        return SortExec(plan.schema, build_executor(plan.child), plan.items)
    if isinstance(plan, PWindow):
        from tidb_tpu.executor.window import WindowExec

        return WindowExec(plan.schema, build_executor(plan.child), plan.func,
                          plan.args, plan.partition_by, plan.order_by,
                          plan.out_uid, plan.out_type, plan.params,
                          frame=plan.frame)
    if isinstance(plan, PTopN):
        fused = _try_fused_scan_topn(plan)
        if fused is not None:
            return fused
        return TopNExec(plan.schema, build_executor(plan.child), plan.items, plan.count, plan.offset)
    if isinstance(plan, PLimit):
        return LimitExec(plan.schema, build_executor(plan.child), plan.count, plan.offset)
    if isinstance(plan, PUnion):
        return UnionExec(plan.schema, [build_executor(c) for c in plan.children])

    raise PlanError(f"no executor for {type(plan).__name__}")
