"""TableScanExec: stream table partitions to device through a fused
filter/project fragment.

This is the distsql/coprocessor boundary of the reference collapsed onto
host->HBM staging: each partition slice becomes a fixed-capacity Chunk,
and one jitted fragment (pushed filter + any stacked Selection/Projection
ops) runs per chunk. The same compiled fragment is reused for every chunk
of the table — shapes are static by construction.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from tidb_tpu.chunk.chunk import Chunk
from tidb_tpu.chunk.column import Column
from tidb_tpu.executor.base import ExecContext, Executor
from tidb_tpu.expression.compiler import compile_expr, compile_predicate
from tidb_tpu.planner.binder import PlanCol
from tidb_tpu.utils.jitcache import cached_jit

__all__ = ["TableScanExec", "PointGetExec", "IndexRangeScanExec",
           "make_pipeline_fn", "SelectionExec", "ProjectionExec"]


def make_pipeline_fn(stages: List) -> Callable:
    """Compose stages into one Chunk->Chunk function to be jitted.

    Each stage is ("filter", ir) or ("project", [(uid, ir)], keep_input:bool).
    """
    compiled = []
    for kind, payload in stages:
        if kind == "filter":
            compiled.append(("filter", compile_predicate(payload)))
        else:
            exprs = [(uid, compile_expr(ir)) for uid, ir in payload]
            compiled.append(("project", exprs))

    def run(chunk: Chunk) -> Chunk:
        for kind, fn in compiled:
            if kind == "filter":
                chunk = chunk.filter(fn(chunk))
            else:
                chunk = chunk.project({uid: f(chunk) for uid, f in fn})
        return chunk

    return run


class TableScanExec(Executor):
    def __init__(self, schema: List[PlanCol], table, stages: List,
                 out_schema: Optional[List[PlanCol]] = None,
                 prune_bounds=()):
        super().__init__(out_schema or schema, [])
        self.scan_schema = schema  # storage columns staged (pre-pipeline)
        self.table = table
        self.stages = stages
        self.prune_bounds = prune_bounds  # zone-consultable pushed bounds
        self._fn = None
        self._slices = []
        self._i = 0
        self._seg_chunks = []   # (Segment, rel_start, rel_end) to stage
        self._seg_i = 0
        self._seg_fn = None
        self._pin = None
        self._scan_counted = False

    def _count_scan(self) -> None:
        """Register as a lock-free reader of the table's live arrays for
        the scan's whole lifetime (paged cursors keep scans open past
        their statement; point/index paths hold physical row ids): a
        CLUSTER BY permute refuses while any scan is counted."""
        guard = getattr(self.table, "txn_guard", None)
        if guard is not None and not self._scan_counted:
            guard.scan_enter()
            self._scan_counted = True

    def open(self, ctx: ExecContext) -> None:
        self.ctx = ctx
        cap = ctx.chunk_capacity
        self._count_scan()
        self._fn = (
            cached_jit("pipeline", repr(self.stages), lambda: make_pipeline_fn(self.stages))
            if self.stages
            else None
        )
        self._slices = []
        self._seg_chunks = []
        self._seg_i = 0
        self._seg_fn = None
        self._pin = None
        if self.table is not None:
            tail_start = 0
            if ctx.columnar_enable:
                tail_start = self._open_segments(ctx, cap)
            n = self.table.n
            for s in range(tail_start, max(n, 1), cap):
                self._slices.append((s, min(s + cap, n)))
            if n <= tail_start:
                self._slices = []
        else:
            # dual table: one empty-schema row (SELECT without FROM)
            self._slices = [None]
        self._i = 0

    def _open_segments(self, ctx: ExecContext, cap: int) -> int:
        """Plan the segment portion of the scan: consult zone maps to
        skip whole segments before any host→device staging, build the
        fused decode+pipeline program, and register the spill pin on
        the statement tracker. Returns the first delta (uncovered) row."""
        from tidb_tpu.columnar.store import ScanPin, store_for
        from tidb_tpu.ops.segment_scan import (
            make_segment_scan_fn,
            segment_scan_key,
        )

        store = store_for(
            self.table, segment_rows=ctx.segment_rows,
            delta_rows=ctx.segment_delta_rows,
            spill_dir=ctx.columnar_spill_dir or None,
            compaction=ctx.compaction_enable)
        if store is None:
            return 0
        # the pin exists BEFORE planning so every snapshot segment is
        # reference-counted against a concurrent store invalidation
        # from the moment this scan learns about it
        self._pin = ScanPin(store, ctx.mem_tracker)
        segs, pruned, covered = store.plan_scan(self.prune_bounds,
                                                pin=self._pin)
        self.stats.segs_scanned += len(segs)
        self.stats.segs_pruned += pruned
        # segment chunks size to the SEGMENT, not the plan's chunk
        # capacity: padding a 64k-row segment into a 1M-row buffer
        # would stage mostly zeros and erase the pruning win. One
        # shared power-of-two capacity keeps a single trace across
        # every segment chunk (the tail partial included).
        seg_cap = 1
        while seg_cap < min(store.segment_rows, cap):
            seg_cap *= 2
        self._seg_cap = seg_cap
        for seg in segs:
            for s in range(0, seg.rows, seg_cap):
                self._seg_chunks.append((seg, s, min(s + seg_cap, seg.rows)))
        if self._seg_chunks:
            col_types = [(c.uid, c.type_) for c in self.scan_schema]
            stages = self.stages
            self._seg_fn = cached_jit(
                "segscan", segment_scan_key(stages, col_types),
                lambda: make_segment_scan_fn(stages, col_types))
        else:
            self._pin.close()  # nothing to stage: drop the refs now
            self._pin = None
        return covered

    def _stage_segment(self, seg, s: int, e: int) -> Chunk:
        """Stage one segment sub-range as a Chunk through the fused
        decode+pipeline program. The narrow encoded bytes are what
        crosses to the device; live-row visibility is read fresh from
        the table's MVCC arrays, so deletes/txn markers since the
        segment build are honored exactly."""
        self._pin.touch(seg)
        cap = self._seg_cap
        n = e - s
        data, valid, refs = {}, {}, {}
        for c in self.scan_schema:
            if c.name == "__rowid__":
                d = np.zeros(cap, dtype=np.int64)
                d[:n] = np.arange(seg.start + s, seg.start + e,
                                  dtype=np.int64)
                v = np.zeros(cap, dtype=np.bool_)
                v[:n] = True
            else:
                enc, sd, sv = seg.col(c.name)
                d = np.zeros(cap, dtype=sd.dtype)
                d[:n] = sd[s:e]
                v = np.zeros(cap, dtype=np.bool_)
                v[:n] = sv[s:e]
                if enc.kind == "for":
                    refs[c.uid] = np.int64(enc.ref)
            data[c.uid] = d
            valid[c.uid] = v
        sel = np.zeros(cap, dtype=np.bool_)
        sel[:n] = self.table.live_mask(
            seg.start + s, seg.start + e,
            read_ts=self.ctx.read_ts, marker=self.ctx.txn_marker)
        return self._seg_fn(data, valid, refs, sel)

    def close(self) -> None:
        if self._pin is not None:
            self._pin.close()
            self._pin = None
        if self._scan_counted:
            self._scan_counted = False
            self.table.txn_guard.scan_exit()
        super().close()

    def next(self) -> Optional[Chunk]:
        while self._seg_i < len(self._seg_chunks):
            seg, s, e = self._seg_chunks[self._seg_i]
            self._seg_i += 1
            chunk = self._stage_segment(seg, s, e)
            self.stats.chunks += 1
            return chunk
        while self._i < len(self._slices):
            sl = self._slices[self._i]
            self._i += 1
            cap = self.ctx.chunk_capacity
            if sl is None:
                sel = np.zeros(cap, dtype=np.bool_)
                sel[0] = True
                chunk = Chunk({}, sel)
            else:
                start, end = sl
                n = end - start
                cols = {}
                for c in self.scan_schema:
                    if c.name == "__rowid__":
                        # physical-rowid pseudo-column (multi-table DML)
                        data = np.arange(start, end, dtype=np.int64)
                        valid = np.ones(n, dtype=np.bool_)
                    else:
                        data, valid = self.table.column_slice(c.name, start, end)
                    cols[c.uid] = Column.from_numpy(data, c.type_, valid=valid, capacity=cap)
                live = np.zeros(cap, dtype=np.bool_)
                live[:n] = self.table.live_mask(
                    start, end, read_ts=self.ctx.read_ts, marker=self.ctx.txn_marker
                )
                chunk = Chunk(cols, live)
            if self._fn is not None:
                chunk = self._fn(chunk)
            self.stats.chunks += 1
            return chunk
        return None

    def _emit_rows(self, rows) -> Chunk:
        """Materialize a physical row-id set into one pow2-capacity
        chunk and run the eager residual pipeline — shared by the point
        and range index access paths."""
        cap = 8
        while cap < len(rows):
            cap *= 2
        cols = {}
        for c in self.scan_schema:
            if c.name == "__rowid__":
                d = np.asarray(rows, dtype=np.int64)
                v = np.ones(len(rows), dtype=np.bool_)
            else:
                d = self.table.data[c.name][rows]
                v = self.table.valid[c.name][rows]
            cols[c.uid] = Column.from_numpy(d, c.type_, valid=v, capacity=cap)
        sel = np.zeros(cap, dtype=np.bool_)
        sel[: len(rows)] = True
        chunk = Chunk(cols, sel)
        if self._fn is not None:
            chunk = self._fn(chunk)
        self.stats.chunks += 1
        return chunk


class PointGetExec(TableScanExec):
    """O(log n) unique-index point lookup feeding one small chunk (ref:
    executor/point_get.go PointGetExecutor). The full pushed filter
    still runs over the fetched rows, so residual conjuncts compose,
    and MVCC visibility is applied by index_lookup itself."""

    def __init__(self, schema, table, stages, index_name, key_values,
                 out_schema=None):
        super().__init__(schema, table, stages, out_schema)
        self.index_name = index_name
        self.key_values = key_values

    def open(self, ctx: ExecContext) -> None:
        # deliberately NOT TableScanExec.open(): that would mint a
        # literal-keyed jitted pipeline per ad-hoc point query (a fresh
        # XLA compile each time) and churn the bounded jit LRU. The
        # handful of fetched rows evaluate eagerly instead.
        Executor.open(self, ctx)
        self.ctx = ctx
        self._count_scan()
        self._fn = make_pipeline_fn(self.stages) if self.stages else None
        rows = self.table.index_lookup(
            self.index_name, self.key_values,
            read_ts=ctx.read_ts, marker=ctx.txn_marker)
        self._rows = rows
        self._slices = [("point", None)]  # one emission
        self._i = 0

    def next(self) -> Optional[Chunk]:
        if self._i >= len(self._slices):
            return None
        self._i += 1
        return self._emit_rows(self._rows)


class RowIdScanExec(TableScanExec):
    """Base for access paths that resolve to a compact row-id set
    (index ranges, pruned partitions) and stage only those rows (ref:
    executor's IndexLookUpExecutor index→table double read,
    SURVEY.md:91). Like PointGetExec, the pipeline runs eagerly —
    the row sets come from literal-keyed probes and a jitted pipeline
    per ad-hoc probe would churn XLA compiles — but rows stream in
    chunk_capacity batches, so a wide set behaves like a pre-filtered
    scan, not one giant gather."""

    def _row_ids(self, ctx: ExecContext):
        raise NotImplementedError

    def open(self, ctx: ExecContext) -> None:
        Executor.open(self, ctx)
        self.ctx = ctx
        self._count_scan()
        self._fn = make_pipeline_fn(self.stages) if self.stages else None
        rows = self._row_ids(ctx)
        self._rows = rows
        cap = ctx.chunk_capacity
        self._slices = [(s, min(s + cap, len(rows)))
                        for s in range(0, len(rows), cap)] or [(0, 0)]
        self._i = 0

    def next(self) -> Optional[Chunk]:
        if self._i >= len(self._slices):
            return None
        start, end = self._slices[self._i]
        self._i += 1
        return self._emit_rows(self._rows[start:end])


class IndexRangeScanExec(RowIdScanExec):
    """Index range access: binary-search the sorted index cache into a
    compact row-id set."""

    def __init__(self, schema, table, stages, index_name, eq_values,
                 range_lo, range_hi, lo_incl, hi_incl, out_schema=None):
        super().__init__(schema, table, stages, out_schema)
        self.index_name = index_name
        self.eq_values = eq_values
        self.range_lo = range_lo
        self.range_hi = range_hi
        self.lo_incl = lo_incl
        self.hi_incl = hi_incl

    def _row_ids(self, ctx: ExecContext):
        return self.table.index_range_lookup(
            self.index_name, self.eq_values, self.range_lo, self.range_hi,
            self.lo_incl, self.hi_incl,
            read_ts=ctx.read_ts, marker=ctx.txn_marker)


class PartitionScanExec(RowIdScanExec):
    """Pruned partitioned-table access: reads only the matching
    partitions' cached row ids (storage/table.py partition_rows)."""

    def __init__(self, schema, table, stages, part_ids, out_schema=None):
        super().__init__(schema, table, stages, out_schema)
        self.part_ids = part_ids

    def _row_ids(self, ctx: ExecContext):
        return self.table.partition_rows(
            self.part_ids, read_ts=ctx.read_ts, marker=ctx.txn_marker)


class SelectionExec(Executor):
    """Standalone filter for positions where fusion into a scan fragment
    wasn't possible (e.g. above an aggregate for HAVING)."""

    def __init__(self, schema, child: Executor, cond):
        super().__init__(schema, [child])
        self.cond = cond
        self._fn = None

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        def build():
            pred = compile_predicate(self.cond)
            return lambda ch: ch.filter(pred(ch))

        self._fn = cached_jit("filter", repr(self.cond), build)

    def next(self) -> Optional[Chunk]:
        ch = self.children[0].next()
        if ch is None:
            return None
        return self._fn(ch)


class ProjectionExec(Executor):
    def __init__(self, schema, child: Executor, exprs):
        super().__init__(schema, [child])
        self.exprs = exprs
        self._fn = None

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        uids = [c.uid for c in self.schema]

        def build():
            pairs = [(uid, compile_expr(e)) for uid, e in zip(uids, self.exprs)]
            return lambda ch: ch.project({uid: f(ch) for uid, f in pairs})

        self._fn = cached_jit("project", repr(list(zip(uids, self.exprs))), build)

    def next(self) -> Optional[Chunk]:
        ch = self.children[0].next()
        if ch is None:
            return None
        return self._fn(ch)
