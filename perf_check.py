#!/usr/bin/env python
"""Per-round perf regression harness (VERDICT r3 weak #4).

Runs the pinned-seed, pinned-SF engine configs RUN-ALONE and asserts
each stays within a band of the committed floor in PERF_FLOOR.json.
Exits 1 on a breach with a diff table; exits 2 (inconclusive, NOT a
failure) if the machine was visibly busy — a perturbed number must
never be mistaken for a regression, and vice versa.

    python perf_check.py            # check against committed floors
    python perf_check.py --set      # (re)write floors from this run

Floors are per-platform (cpu/tpu): the committed file may carry both.
The band: measured >= floor * (1 - TOLERANCE). TOLERANCE covers normal
machine-to-machine jitter; a real regression (like r3's unexplained
-38% on Q1) blows straight through it.
"""

import json
import os
import sys
import time

TOLERANCE = float(os.environ.get("PERF_TOLERANCE", "0.25"))
REPS = int(os.environ.get("PERF_REPS", "3"))
FLOOR_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "PERF_FLOOR.json")
BUSY_LOAD = float(os.environ.get("PERF_BUSY_LOAD", "1.5"))


def main():
    import bench  # repo-root bench module: reuse lock + load machinery

    setting = "--set" in sys.argv

    lock = bench.chip_lock()
    if lock[0] == "unavailable":
        # chip held by a live client: measure CPU-only, never start a
        # second TPU client (overlapping clients wedge the tunnel)
        os.environ["BENCH_PLATFORM"] = "cpu"
        print(f"chip lock {lock[1]}")
    try:
        load0 = bench.machine_load()
        if load0["loadavg"][0] > BUSY_LOAD or load0.get("busy_procs"):
            print(f"INCONCLUSIVE: machine busy before run: {load0}")
            if not setting:
                sys.exit(2)

        # pin platform the same way bench does (probe; fall back to cpu)
        platform, detail = bench.pick_platform()
        if platform != "default":
            os.environ["JAX_PLATFORMS"] = platform

        import tidb_tpu  # noqa: F401
        import jax

        if platform != "default":
            jax.config.update("jax_platforms", platform)
        plat_key = jax.devices()[0].platform

        from tidb_tpu.parallel import make_mesh
        from tidb_tpu.session import Session
        from tidb_tpu.storage.tpch import load_tpch
        from tidb_tpu.storage.tpch_queries import Q

        mesh = make_mesh()
        s = Session(chunk_capacity=1 << 20, mesh=mesh)
        counts = load_tpch(s.catalog, sf=1.0)  # pinned SF + datagen seed
        rows = counts["lineitem"]

        def best_of(sql, reps=REPS):
            s.query(sql)  # warm/compile
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                s.query(sql)
                best = min(best, time.perf_counter() - t0)
            return best

        measured = {}
        measured["q1_rows_per_sec"] = round(rows / best_of(Q["q1"][0]), 1)
        measured["q6_rows_per_sec"] = round(rows / best_of(Q["q6"][0]), 1)
        jq = ("select count(*) as n, sum(l_quantity) as q from lineitem "
              "join orders on l_orderkey = o_orderkey "
              "where o_totalprice > 100000")
        measured["join_rows_per_sec"] = round(rows / best_of(jq), 1)

        # plan-cache FIXED floors (not PERF_FLOOR.json bands): a change
        # that silently disables the cache must fail loudly. The ratio
        # is self-relative (cold and warm run back to back), so it is
        # robust to absolute machine speed. Best-of-3 absorbs jitter.
        # Floor re-anchored at 1.8 (ISSUE 19 satellite; was 3.0): the
        # committed tree measures best-of-5 = 2.16 (range 1.78-2.16)
        # on this box, so 3.0 flagged every healthy run. 1.8 keeps the
        # invariant being protected — a silently-disabled cache
        # collapses the ratio to ~1.0 — with ~17% headroom under the
        # measured best. Rationale recorded in PERF_FLOOR.json under
        # "fixed_floor_provenance".
        pc_ratio, pc_hit = 0.0, 0.0
        for _ in range(3):
            pc = bench.bench_plan_cache({})
            pc_ratio = max(pc_ratio, pc["warm_over_cold"])
            pc_hit = max(pc_hit, pc["hit_rate"])
        print(f"plan_cache_warm_over_cold {pc_ratio}  (need >= 1.8)")
        print(f"plan_cache_hit_rate      {pc_hit}  (need >= 0.9)")
        pc_bad = []
        if pc_ratio < 1.8:
            pc_bad.append(f"plan_cache_warm_over_cold={pc_ratio} < 1.8")
        if pc_hit < 0.9:
            pc_bad.append(f"plan_cache_hit_rate={pc_hit} < 0.9")

        # join microbench FIXED floors (ISSUE 3): warm probe >= 3x cold
        # (a warm join that re-traces pays cold-compile cost every run
        # and fails this), 0 warm recompiles, and result-hash equality
        # with the sqlite oracle. Best-of-3 on the ratio absorbs jitter;
        # correctness floors must hold on EVERY run.
        jm_ratio = 0.0
        jm_bad = {}  # keyed: a config failing on every retry reports once
        for _ in range(3):
            jm = bench.bench_join_micro({})
            head = jm["configs"][0]
            jm_ratio = max(jm_ratio, head["warm_over_cold"])
            for cfg in jm["configs"]:
                tag = f"{cfg['build_rows']}x{cfg['probe_rows']}"
                if cfg["check"] != "ok" or not cfg["hash_equal"]:
                    jm_bad[f"join_result_hash[{tag}]"] = cfg["check"]
                if cfg["warm_recompiles"] != 0:
                    jm_bad[f"join_warm_recompiles[{tag}]"] = (
                        f"{cfg['warm_recompiles']} != 0")
            if jm_ratio >= 3.0 and not jm_bad:
                break
        print(f"join_warm_over_cold      {jm_ratio}  (need >= 3.0)")
        pc_bad.extend(f"{k}={v}" for k, v in jm_bad.items())
        if jm_ratio < 3.0:
            pc_bad.append(f"join_warm_over_cold={jm_ratio} < 3.0")

        # OLTP serving FIXED floors (ISSUE 7): coalesced throughput must
        # beat unbatched at >= 8 clients and by >= 1.5x at 16, with the
        # plan-cache hit rate preserved and every statement's result
        # byte-identical to serial execution. Ratios are self-relative
        # (both arms run back to back through the SAME scheduler), so
        # they're robust to machine speed; best-of-3 absorbs jitter.
        # Correctness floors (oracle, hit rate) must hold on EVERY run.
        ol_bad = {}
        ol_speed = {}
        for _ in range(3):
            ol = bench.bench_oltp({})
            for cfg in ol["configs"]:
                nc = cfg["clients"]
                ol_speed[nc] = max(ol_speed.get(nc, 0.0), cfg["speedup"])
                if cfg["oracle"] != "ok":
                    ol_bad[f"oltp_oracle[{nc}]"] = cfg["oracle"]
                if cfg["hit_rate"] < 0.9:
                    ol_bad[f"oltp_hit_rate[{nc}]"] = (
                        f"{cfg['hit_rate']} < 0.9")
            if (not ol_bad and ol_speed.get(8, 0.0) >= 1.0
                    and ol_speed.get(16, 0.0) >= 1.5):
                break
        for nc, need in ((8, 1.0), (16, 1.5)):
            got = ol_speed.get(nc, 0.0)
            print(f"oltp_batched_speedup[{nc}] {got}  (need >= {need})")
            if got < need:
                ol_bad[f"oltp_batched_speedup[{nc}]"] = f"{got} < {need}"
        pc_bad.extend(f"{k}={v}" for k, v in ol_bad.items())

        # fused-pipeline FIXED floors (ISSUE 9). The core acceptance is
        # the DISPATCH budget: a warm Q1/Q6 fragment on the single-chip
        # spine must issue single-digit device round trips (engine
        # counter) — on the tunneled TPU each dispatch floors at ~0.5s,
        # so the chunk-synced path's ~40 dispatches vs the pipeline's
        # <=9 IS a multi-x win there. On XLA:CPU (this harness) Q1 is
        # compute-bound and dispatch-insensitive, so the wall-clock
        # ratio floors split: the staging-bound Q6 must show the
        # fusion + overlap + device-cache win (>=1.5x best-of-3
        # interleaved; measured 1.6-2.4x), and the compute-bound Q1
        # must not regress under fusion (>=0.9x; measured 1.02-1.09x —
        # its win on CPU is the dispatch budget, not wall clock).
        # Correctness floors (arms identical + sqlite oracle) hold on
        # EVERY run.
        pl_bad = {}
        pl_speed = {"q1": 0.0, "q6": 0.0}
        # best-of-5 (early exit on pass, so a healthy tree still pays
        # one rep): inside a full perf_check run the classic arm
        # arrives warm from the preceding blocks and its wall clock
        # compresses ~20%, which pushes single reps of the razor-thin
        # 1.5x Q6 ratio under the floor while isolated runs clear it
        for _ in range(5):
            pl = bench.bench_pipeline({})
            for qn, q in pl["queries"].items():
                pl_speed[qn] = max(pl_speed[qn], q["fused_over_unfused"])
                if q["fused_warm_dispatches"] > 9:
                    pl_bad[f"pipeline_dispatches[{qn}]"] = (
                        f"{q['fused_warm_dispatches']} > 9")
                if not q["hash_equal"] or q["check"] != "ok":
                    pl_bad[f"pipeline_oracle[{qn}]"] = q["check"]
            if (not pl_bad and pl_speed["q6"] >= 1.5
                    and pl_speed["q1"] >= 0.9):
                break
        print(f"pipeline_q6_speedup      {pl_speed['q6']}  (need >= 1.5)")
        print(f"pipeline_q1_speedup      {pl_speed['q1']}  (need >= 0.9)")
        if pl_speed["q6"] < 1.5:
            pl_bad["pipeline_q6_speedup"] = f"{pl_speed['q6']} < 1.5"
        if pl_speed["q1"] < 0.9:
            pl_bad["pipeline_q1_speedup"] = f"{pl_speed['q1']} < 0.9"
        pc_bad.extend(f"{k}={v}" for k, v in pl_bad.items())

        # fused scan→probe FIXED floors (ISSUE 10). The Q18 fragment
        # shape warm: <= 12 device dispatches (fused chunk programs +
        # ONE window fetch + agg, build and staged scan device-cached)
        # and >= 1.3x over the chunk-synced classic tree on CPU
        # (best-of-3, interleaved arms — the fused win here is the
        # cached build + single-dispatch chunks; on the tunneled TPU
        # each saved dispatch is ~0.5s). Correctness floors hold EVERY
        # run: arms + oracle byte-identical, and the hash-table probe
        # (mode=xla — the TPU-shaped kernel run via XLA window scans)
        # result-equal to searchsorted on the same fused fragment.
        jfu_bad = {}
        jfu_speed = 0.0
        for _ in range(3):
            jfu = bench.bench_join_fused({})
            jfu_speed = max(jfu_speed, jfu["fused_over_classic"])
            if jfu["fused_warm_dispatches"] > 12:
                jfu_bad["join_fused_dispatches"] = (
                    f"{jfu['fused_warm_dispatches']} > 12")
            if not jfu["hash_equal"] or jfu["check"] != "ok":
                jfu_bad["join_fused_oracle"] = jfu["check"]
            if not jfu["probe_modes_equal"]:
                jfu_bad["join_probe_mode_equivalence"] = (
                    jfu.get("mode_mismatch", "table != searchsorted"))
            # ISSUE 15: the fused (no-push) plan must be CHOSEN by the
            # plan-feedback store with tidb_opt_agg_push_down at its
            # default — the bench no longer pins the sysvar
            if not jfu["chosen_by_feedback"]:
                jfu_bad["join_fused_feedback"] = (
                    "fused plan not selected by plan feedback")
            if not jfu_bad and jfu_speed >= 1.3:
                break
        print(f"join_fused_speedup       {jfu_speed}  (need >= 1.3)")
        if jfu_speed < 1.3:
            jfu_bad["join_fused_speedup"] = f"{jfu_speed} < 1.3"
        # probe-kernel counts oracle (chip-free half of the mode-
        # equivalence proof): must match on every size, every run
        pk = bench.bench_probe({})
        if not pk["counts_match"]:
            jfu_bad["probe_kernel_counts"] = "table counts != searchsorted"
        pc_bad.extend(f"{k}={v}" for k, v in jfu_bad.items())

        # columnar segment store FIXED floors (ISSUE 8). Zone pruning:
        # TPC-H Q6 at SF1 over time-ordered lineitem must skip >= 50%
        # of segments (the ENGINE-reported counter), run >= 2x faster
        # than the unpruned scan (self-relative: both arms back to
        # back), and match the exact scaled-int sqlite oracle. Budget:
        # q18 capped below the store's resident bytes must complete
        # via segment spill (spill-out counter moves) with rows
        # byte-identical to the resident run.
        zp_bad = {}
        # best-of-3 like the pipeline/oltp/topn blocks: the ratio sits
        # near its floor (unpruned arm ~170ms at SF1), so one descheduled
        # rep flips the verdict — correctness gates still check EVERY run
        zp_speed = 0.0
        for _ in range(3):
            zp = bench.bench_zone_pruning({}, sf=1.0)
            zp_speed = max(zp_speed, zp["pruned_over_unpruned"])
            if zp["check"] != "ok" or zp["pruned_fraction"] < 0.5:
                break
            if zp_speed >= 2.0:
                break
        print(f"zone_pruned_fraction     {zp['pruned_fraction']}  "
              "(need >= 0.5)")
        print(f"zone_pruned_speedup      {zp_speed}  (need >= 2.0)")
        if zp["check"] != "ok":
            zp_bad["zone_pruning_oracle"] = zp["check"]
        if zp["pruned_fraction"] < 0.5:
            zp_bad["zone_pruned_fraction"] = (
                f"{zp['pruned_fraction']} < 0.5")
        if zp_speed < 2.0:
            zp_bad["zone_pruned_speedup"] = f"{zp_speed} < 2.0"
        bq = bench.bench_budget_q18(s.catalog)
        print(f"q18_budget_hash_equal    {bq['hash_equal']}  "
              f"(spill out {bq['spill_out_bytes'] >> 20}MiB)")
        if not bq["hash_equal"]:
            zp_bad["q18_budget_hash"] = "budgeted != resident rows"
        if bq["spill_out_bytes"] <= 0:
            zp_bad["q18_budget_spill"] = "no segment spill engaged"
        pc_bad.extend(f"{k}={v}" for k, v in zp_bad.items())

        # fused TopN FIXED floors (ISSUE 18): ORDER BY + LIMIT over a
        # staged scan runs entirely on device — bounded top-k state
        # merged per chunk (single-key candidate cut + variadic merge),
        # ONE fetch at finalize — and must beat the classic
        # materializing sort >= 1.5x (best-of-3, interleaved arms;
        # measured ~3x on CPU: the classic arm pays full-column host
        # materialization + np.lexsort per query). Correctness floors
        # hold EVERY run: fused == classic rows, sort-key column equal
        # to the sqlite oracle, the FusedScanTopN operator actually
        # attributed in EXPLAIN ANALYZE (a silent fallback must not
        # masquerade as a fused win), and the warm dispatch budget.
        tn_bad = {}
        tn_speed = {}
        for _ in range(3):
            tn = bench.bench_topn_fused({})
            for qn, q in tn["queries"].items():
                tn_speed[qn] = max(tn_speed.get(qn, 0.0),
                                   q["fused_over_classic"])
                if q["check"] != "ok" or not q["hash_equal"]:
                    tn_bad[f"topn_{qn}_oracle"] = q["check"]
                if not q["fused_engaged"]:
                    tn_bad[f"topn_{qn}_engaged"] = "no FusedScanTopN op"
                if q["fused_warm_dispatches"] > 4:
                    tn_bad[f"topn_{qn}_dispatches"] = (
                        f"{q['fused_warm_dispatches']} > 4")
            if not tn_bad and tn_speed and min(tn_speed.values()) >= 1.5:
                break
        for qn in sorted(tn_speed):
            print(f"topn_fused_speedup[{qn}] {tn_speed[qn]}  (need >= 1.5)")
            if tn_speed[qn] < 1.5:
                tn_bad[f"topn_{qn}_speedup"] = f"{tn_speed[qn]} < 1.5"
        pc_bad.extend(f"{k}={v}" for k, v in tn_bad.items())

        # TPC-H 22-query grid gate (ISSUE 18): every query exact vs the
        # indexed sqlite oracle at SF 0.1, with fused operators
        # attributed on the bulk of the plans (EXPLAIN ANALYZE physical
        # tree). Correctness-only gate — per-query wall times are
        # captured in BENCH_r*, not floored here.
        gr = bench.bench_tpch_grid({}, reps=1)
        gr_exact = sum(1 for q in gr["queries"].values()
                       if q.get("check") == "ok")
        print(f"tpch_grid_exact          {gr_exact}/22")
        print(f"tpch_grid_fused_queries  {gr['fused_queries']}  "
              "(need >= 12)")
        if not gr["all_exact"]:
            bad_q = [k for k, v in gr["queries"].items()
                     if v.get("check") != "ok"
                     or not v.get("device_arm_equal", True)]
            pc_bad.append(f"tpch_grid_exact={bad_q}")
        if gr["fused_queries"] < 12:
            pc_bad.append(f"tpch_grid_fused={gr['fused_queries']} < 12")

        # flagship-config ABSOLUTE floors (ISSUE 18): Q18 / SSB Q3.2 /
        # TPC-DS Q95 at the same pinned SFs bench.py uses, riding the
        # PERF_FLOOR band like q1/q6 — a regression in the join spine,
        # star-join, or semi-join paths must trip the band even when
        # the self-relative fixed floors above still pass. Fresh
        # session per config, working set dropped between (the SF1 set
        # stays resident like in bench.main, so floors and checks see
        # the same memory pressure).
        try:
            import gc

            from tidb_tpu.storage.ssb import SSB_QUERIES, load_ssb
            from tidb_tpu.storage.tpcds import Q95, load_tpcds_q95

            def flagship(loader, sf, sql, rows_key):
                fs = Session(chunk_capacity=1 << 20, mesh=mesh)
                cts = loader(fs.catalog, sf=sf)
                fs.execute("SET tidb_slow_log_threshold = 300000")
                fs.query(sql)  # warm
                best = float("inf")
                for _ in range(REPS):
                    t0 = time.perf_counter()
                    fs.query(sql)
                    best = min(best, time.perf_counter() - t0)
                del fs
                gc.collect()
                return round(cts[rows_key] / best, 1)

            measured["q18_rows_per_sec"] = flagship(
                load_tpch, 0.2, Q["q18"][0], "lineitem")
            measured["ssb_q32_rows_per_sec"] = flagship(
                load_ssb, 0.1, SSB_QUERIES["q3.2"], "lineorder")
            measured["tpcds_q95_rows_per_sec"] = flagship(
                load_tpcds_q95, 0.2, Q95, "web_sales")
        except Exception as e:  # noqa: BLE001
            pc_bad.append(f"flagship_floors={type(e).__name__}: {e}"[:200])

        # sharded scale-out FIXED floors (ISSUE 13): the same scan-agg
        # at 1->2->4 workers over SHARD BY placement must show >= 1.6x
        # critical-path scaling at 4 workers (max per-owner partial +
        # measured coordinator overhead — the wall clock a multi-host
        # fleet achieves; this harness has 1 core, so raw wall clock is
        # reported but not gated) with every arm's full result
        # hash-equal to the serial oracle on EVERY run. Best-of-3 on
        # the ratio absorbs jitter.
        mc_bad = {}
        mc_speed = 0.0
        for _ in range(3):
            mc = bench.bench_multichip({})
            mc_speed = max(mc_speed, mc["speedup_4w"])
            if not mc["hash_equal"]:
                mc_bad["multichip_oracle"] = "arm hash != serial oracle"
            if not mc_bad and mc_speed >= 1.6:
                break
        print(f"multichip_speedup_4w     {mc_speed}  (need >= 1.6)")
        if mc_speed < 1.6:
            mc_bad["multichip_speedup_4w"] = f"{mc_speed} < 1.6"
        pc_bad.extend(f"{k}={v}" for k, v in mc_bad.items())

        # mixed 90/10 group-commit FIXED floors (ISSUE 17): with the
        # gather window on, the 10% autocommit point updates coalesce
        # through the same window as the reads — the mix must beat the
        # all-singleton arm >= 3x self-relative at 16 clients (measured
        # ~7x), and the final table state hash must equal the serial
        # oracle's on EVERY run (the updates commute, so any
        # interleaving must land on the same state). The absolute
        # stmts/s rides the PERF_FLOOR band below.
        mx_bad = {}
        mx_speed, mx_rps = 0.0, 0.0
        for _ in range(3):
            mx = bench.bench_mixed({})
            mx_speed = max(mx_speed, mx["group_commit_speedup"])
            mx_rps = max(mx_rps, mx["mixed_90_10_stmts_per_sec"])
            if mx["oracle"] != "ok":
                mx_bad["mixed_oracle"] = mx["oracle"]
            if not mx_bad and mx_speed >= 3.0:
                break
        print(f"mixed_group_commit_speedup {mx_speed}  (need >= 3.0)")
        if mx_speed < 3.0:
            mx_bad["mixed_group_commit_speedup"] = f"{mx_speed} < 3.0"
        measured["mixed_90_10_stmts_per_sec"] = mx_rps
        pc_bad.extend(f"{k}={v}" for k, v in mx_bad.items())

        # HTAP FIXED floors (ISSUE 17): analytics during sustained
        # ingest with background compaction ON. Correctness every run:
        # the final Q6 with tidb_tpu_compaction=0 byte-identical to ON
        # (the worker moves WHERE the rebuild runs, never what a scan
        # returns), zero ingest errors, compaction actually engaged,
        # and snapshot staleness bounded. Throughput floors ride the
        # PERF_FLOOR band.
        ht_bad = {}
        ht = bench.bench_htap({})
        print(f"htap_flag_off_equal      {ht['flag_off_equal']}")
        print(f"htap_analytics_p99_ms    {ht['analytics_p99_ms']}")
        if not ht["flag_off_equal"]:
            ht_bad["htap_flag_off"] = "compaction=0 != compaction=1 rows"
        if ht["ingest_errors"]:
            ht_bad["htap_ingest_errors"] = str(ht["ingest_errors"][0])
        if sum(ht["compaction"].values()) < 1:
            ht_bad["htap_compaction_engaged"] = "no compaction outcome"
        if ht["staleness_rows_max"] > 256:
            ht_bad["htap_staleness"] = (
                f"{ht['staleness_rows_max']} rows > 256")
        measured["htap_oltp_stmts_per_sec"] = ht["htap_oltp_stmts_per_sec"]
        measured["htap_analytics_qps"] = ht["htap_analytics_qps"]
        pc_bad.extend(f"{k}={v}" for k, v in ht_bad.items())

        # elastic-topology FIXED floors (ISSUE 19): a live 12->24
        # online reshard (shard-function change — every shard moves)
        # under sustained mixed traffic must never fully stall serving:
        # every 1-second window of the run serves at least one
        # successful statement, every oracle-checked read is exact,
        # every acked writer row survives the cutover, and the reshard
        # actually ran. The p99 / throughput-dip numbers are reported
        # as the operator-facing artifact; they ride machine load too
        # hard on this 1-core harness to band.
        el_bad = {}
        el = bench.bench_elastic({})
        print(f"elastic_reshard_s        {el['reshard_s']}")
        print(f"elastic_served_windows   {el['windows_1s']}")
        print(f"elastic_throughput_dip   {el['throughput_dip']}")
        print(f"elastic_read_p99_ms      {el['read_p99_ms']}")
        if not el["served_every_window"]:
            el_bad["elastic_serving_stall"] = (
                f"a 1s window served 0 statements: {el['windows_1s']}")
        if el["check"] != "ok":
            el_bad["elastic_check"] = el["check"]
        if el["reshard_s"] <= 0:
            el_bad["elastic_reshard"] = "reshard did not run"
        pc_bad.extend(f"{k}={v}" for k, v in el_bad.items())

        load1 = bench.machine_load()
        busy_after = load1["loadavg"][0] > BUSY_LOAD or load1.get("busy_procs")

        if setting:
            floors = {}
            if os.path.exists(FLOOR_PATH):
                floors = json.load(open(FLOOR_PATH))
            floors[plat_key] = {
                "floors": measured,
                "set_at": time.strftime("%Y-%m-%d %H:%M:%S"),
                "load": [load0["loadavg"], load1["loadavg"]],
                # ISSUE 16: record WHERE the floor came from so a later
                # check against a different tree warns instead of
                # silently gating changed code with stale numbers
                "provenance": bench.bench_provenance(),
            }
            json.dump(floors, open(FLOOR_PATH, "w"), indent=1)
            print(f"floors[{plat_key}] set: {measured}")
            return

        if not os.path.exists(FLOOR_PATH):
            print("INCONCLUSIVE: no PERF_FLOOR.json committed yet "
                  "(run with --set on an idle machine to create it)")
            sys.exit(2)
        floors = json.load(open(FLOOR_PATH)).get(plat_key)
        if floors is None:
            print(f"INCONCLUSIVE: no committed floor for platform {plat_key}")
            sys.exit(2)
        # provenance drift is a WARNING, not a failure: old floors are
        # still a valid lower bound, but the reader should know the
        # numbers were captured on a different revision (ISSUE 16)
        floor_rev = floors.get("provenance", {}).get("git_rev", "")
        cur_rev = bench.bench_provenance()["git_rev"]
        if floor_rev and cur_rev and floor_rev != cur_rev:
            print(f"WARNING: floors set at rev {floor_rev}, checking rev "
                  f"{cur_rev} — rerun with --set after intentional perf "
                  "changes")
        bad = list(pc_bad)
        for k, floor in floors["floors"].items():
            got = measured.get(k, 0.0)
            need = floor * (1 - TOLERANCE)
            status = "ok" if got >= need else "REGRESSION"
            print(f"{k:24s} floor={floor:>12.1f} need>={need:>12.1f} "
                  f"got={got:>12.1f}  {status}")
            if got < need:
                bad.append(k)
        if bad and busy_after:
            print(f"INCONCLUSIVE: breaches {bad} but machine went busy "
                  f"mid-run: {load1}")
            sys.exit(2)
        if bad:
            print(f"PERF REGRESSION: {bad} (band {TOLERANCE:.0%} below "
                  "committed floor)")
            sys.exit(1)
        print("perf check: all configs within band")
    finally:
        bench.chip_unlock(lock[0])


if __name__ == "__main__":
    main()
