// TPC-H data generator core (the dbgen equivalent of the native
// data-loader tier; ref: the reference ecosystem's external dbgen +
// TiKV-side ingest, which live below the SQL layer as native code).
//
// Generates the two big tables (orders, lineitem) directly in the
// engine's device representation: int64 columns, scale-2 cents for
// money, days-since-epoch dates, and dictionary CODES for the
// low-cardinality string columns (the Python side supplies the sorted
// pools). Strings for the big tables never exist as Python objects —
// the columnar buffers fill at memcpy-like speed and stage straight to
// HBM.
//
// Determinism: splitmix64 seeded per (seed, purpose) stream, so
// tpch_sizes and tpch_gen agree on the variable lineitem count.
//
// C ABI only (loaded via ctypes; no pybind11 in this image).

#include <cstdint>
#include <cstring>

namespace {

struct Rng {
    uint64_t s;
    explicit Rng(uint64_t seed) : s(seed) {}
    uint64_t next() {
        uint64_t z = (s += 0x9E3779B97F4A7C15ULL);
        z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
        z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
        return z ^ (z >> 31);
    }
    // uniform in [lo, hi] inclusive
    int64_t uniform(int64_t lo, int64_t hi) {
        return lo + static_cast<int64_t>(next() % static_cast<uint64_t>(hi - lo + 1));
    }
    double real() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
};

// days since epoch for the spec's fixed dates
constexpr int64_t kStart = 8035;    // 1992-01-01
constexpr int64_t kEnd = 10440;     // 1998-08-02
constexpr int64_t kCurrent = 9298;  // 1995-06-17

inline int64_t retail_price(int64_t pk) {
    return 90000 + (pk / 10) % 20001 + 100 * (pk % 1000);
}

}  // namespace

extern "C" {

// Row counts for scale factor sf: orders count and (rng-dependent)
// lineitem count. Must be called before tpch_gen to size the buffers.
void tpch_sizes(double sf, uint64_t seed, int64_t* no_out, int64_t* nl_out) {
    int64_t no = static_cast<int64_t>(1500000.0 * sf);
    if (no < 1) no = 1;
    Rng rng(seed * 2654435761ULL + 1);
    int64_t nl = 0;
    for (int64_t i = 0; i < no; i++) nl += rng.uniform(1, 7);
    *no_out = no;
    *nl_out = nl;
}

// Fill orders + lineitem columns. All pointers are int64 buffers sized
// by tpch_sizes (orders: no; lineitem: nl). *_code columns are indices
// into the sorted pools the caller owns. npart/nsupp/ncust/nclerk give
// the FK domains.
void tpch_gen(
    double sf, uint64_t seed,
    int64_t npart, int64_t nsupp, int64_t ncust, int64_t nclerk,
    // orders
    int64_t* o_orderkey, int64_t* o_custkey, int64_t* o_totalprice,
    int64_t* o_orderdate, int64_t* o_shippriority, int64_t* o_status_code,
    int64_t* o_priority_code, int64_t* o_clerk_code, int64_t* o_comment_code,
    // lineitem
    int64_t* l_orderkey, int64_t* l_partkey, int64_t* l_suppkey,
    int64_t* l_linenumber, int64_t* l_quantity, int64_t* l_extendedprice,
    int64_t* l_discount, int64_t* l_tax, int64_t* l_returnflag_code,
    int64_t* l_linestatus_code, int64_t* l_shipdate, int64_t* l_commitdate,
    int64_t* l_receiptdate, int64_t* l_instruct_code, int64_t* l_shipmode_code,
    int64_t* l_comment_code) {
    int64_t no = static_cast<int64_t>(1500000.0 * sf);
    if (no < 1) no = 1;

    // identical stream to tpch_sizes for the per-order line counts
    Rng line_rng(seed * 2654435761ULL + 1);
    Rng rng(seed * 0x9E3779B97F4A7C15ULL + 7);

    int64_t li = 0;
    for (int64_t o = 0; o < no; o++) {
        int64_t okey = o + 1;
        int64_t lines = line_rng.uniform(1, 7);
        int64_t odate = rng.uniform(kStart, kEnd - 151);

        o_orderkey[o] = okey;
        o_custkey[o] = rng.uniform(1, ncust);
        o_orderdate[o] = odate;
        o_shippriority[o] = 0;
        o_priority_code[o] = rng.uniform(0, 4);
        o_clerk_code[o] = rng.uniform(0, nclerk - 1);
        o_comment_code[o] = rng.uniform(0, 63);

        int64_t total_scale6 = 0;  // sum of extended*(1-d)*(1+t), scale 6
        int64_t n_f = 0;
        for (int64_t j = 0; j < lines; j++, li++) {
            int64_t pk = rng.uniform(1, npart);
            int64_t qty = rng.uniform(1, 50);
            int64_t ext = qty * retail_price(pk);
            int64_t disc = rng.uniform(0, 10);
            int64_t tax = rng.uniform(0, 8);
            int64_t ship = odate + rng.uniform(1, 121);
            int64_t commit = odate + rng.uniform(30, 90);
            int64_t receipt = ship + rng.uniform(1, 30);

            l_orderkey[li] = okey;
            l_partkey[li] = pk;
            l_suppkey[li] = ((pk + rng.uniform(0, 3) * (nsupp / 4 + 1)) % nsupp) + 1;
            l_linenumber[li] = j + 1;
            l_quantity[li] = qty * 100;  // scale-2
            l_extendedprice[li] = ext;
            l_discount[li] = disc;
            l_tax[li] = tax;
            l_shipdate[li] = ship;
            l_commitdate[li] = commit;
            l_receiptdate[li] = receipt;
            // sorted pool {A, N, R}: returned -> A or R, else N
            bool returned = receipt <= kCurrent;
            l_returnflag_code[li] = returned ? (rng.real() < 0.5 ? 0 : 2) : 1;
            // sorted pool {F, O}
            bool open = ship > kCurrent;
            l_linestatus_code[li] = open ? 1 : 0;
            if (!open) n_f++;
            l_instruct_code[li] = rng.uniform(0, 3);
            l_shipmode_code[li] = rng.uniform(0, 6);
            l_comment_code[li] = rng.uniform(0, 63);

            total_scale6 += ext * (100 - disc) * (100 + tax) / 10000;
        }
        o_totalprice[o] = total_scale6;
        // sorted pool {F, O, P}
        o_status_code[o] = (n_f == lines) ? 0 : (n_f == 0 ? 1 : 2);
    }
}

}  // extern "C"
