#!/usr/bin/env python
"""Standing TPU-capture watchdog.

The tunneled TPU chip has been flaky for two rounds (BASELINE.md r2/r3
notes): it may come alive at any hour and numbers must be captured the
moment it does, unattended. This daemon:

  loop:
    - probe the default jax backend in a DETACHED child (never killed:
      killing a mid-claim TPU client wedges the tunnel — BASELINE.md r2)
    - if the probe hangs, WAIT for that child to exit before probing
      again (two overlapping TPU clients also wedge the tunnel)
    - on the first healthy TPU probe: claim the chip ONCE while holding
      the shared chip lock (/tmp/tpu_chip.lock, honored by bench.py),
      run the full 5-config bench -> BENCH_tpu.json, then refresh
      ops/SEGSUM_BENCH.json (the i64 limb kernel has never run on
      silicon), release, and exit.

Every probe attempt and outcome is appended to tpu_watchdog.log with a
timestamp so the log itself is evidence of tunnel liveness (or the lack
of it) across the round.

Run detached:  nohup setsid python tpu_watchdog.py >/dev/null 2>&1 &
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
LOG = os.path.join(REPO, "tpu_watchdog.log")
LOCK = os.environ.get("TPU_CHIP_LOCK", "/tmp/tpu_chip.lock")
HANDOFF = LOCK + ".handoff"
PROBE_DIR = "/tmp/tpu_watch"
PROBE_INTERVAL = float(os.environ.get("TPU_PROBE_INTERVAL", "600"))
PROBE_TIMEOUT = float(os.environ.get("TPU_PROBE_TIMEOUT", "420"))
CAPTURE_ATTEMPTS = int(os.environ.get("TPU_CAPTURE_ATTEMPTS", "3"))
BENCH_OUT = os.path.join(REPO, "BENCH_tpu.json")


def log(msg):
    line = f"{time.strftime('%Y-%m-%d %H:%M:%S')} {msg}"
    with open(LOG, "a") as f:
        f.write(line + "\n")
    print(line, flush=True)


def acquire_lock(why, patience=None):
    """Atomic mkdir lock shared with bench.py so chip clients never
    overlap. Blocks (with periodic logging) until acquired."""
    t0 = time.time()
    while True:
        try:
            os.mkdir(LOCK)
            with open(os.path.join(LOCK, "owner"), "w") as f:
                f.write(f"tpu_watchdog pid={os.getpid()} why={why}\n")
            return True
        except FileExistsError:
            if patience is not None and time.time() - t0 > patience:
                return False
            if int(time.time() - t0) % 600 < 2:
                log(f"waiting on chip lock {LOCK} (held by: "
                    f"{_lock_owner()}) for {why}")
            time.sleep(2)


def _lock_owner():
    try:
        with open(os.path.join(LOCK, "owner")) as f:
            return f.read().strip()
    except OSError:
        return "?"


def release_lock():
    try:
        os.unlink(os.path.join(LOCK, "owner"))
    except OSError:
        pass
    try:
        os.rmdir(LOCK)
    except OSError:
        pass


def bench_wants_chip():
    """True while a live bench has posted the handoff file (VERDICT r4
    weak #1: probes must back off when the bench wants the chip). A
    handoff whose owner pid is dead is stale — remove it."""
    try:
        owner = open(HANDOFF).read().strip()
    except OSError:
        return False
    import re

    m = re.search(r"pid=(\d+)", owner)
    if m is None or not os.path.exists(f"/proc/{m.group(1)}"):
        # dead owner, or malformed/empty (bench SIGKILLed pre-flush):
        # either way nobody is coming back for it
        log(f"removing stale handoff file (owner {owner!r})")
        try:
            os.unlink(HANDOFF)
        except OSError:
            pass
        return False
    return True


def stand_down_while_handoff():
    """Block (never holding the lock) while the bench wants the chip."""
    logged = 0.0
    while bench_wants_chip():
        if time.time() - logged > 600:
            logged = time.time()
            log("bench handoff posted; standing down (no probes)")
        time.sleep(10)


def _missing_count():
    """How many bench configs are still missing/errored in the artifact
    (the progress measure for TPU_CAPTURE_MODE=missing — an error-only
    patch changes the file's mtime but NOT this count). The config list
    itself lives in ONE place: scripts/missing_configs_recapture.py."""
    try:
        extra = json.load(open(BENCH_OUT))["extra"]
    except (OSError, ValueError, KeyError):
        return 99
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import missing_configs_recapture as mcr

        return mcr.missing_count(extra)
    finally:
        sys.path.pop(0)


def probe_once(idx):
    """Detached probe child; returns (status, detail).

    status: 'tpu' (healthy TPU backend), 'cpu' (backend unavailable,
    fast-failed), 'hung' (child still alive at timeout — caller must
    wait for it to exit before any other chip client starts)."""
    os.makedirs(PROBE_DIR, exist_ok=True)
    marker = os.path.join(PROBE_DIR, f"r5_probe_{idx}.json")
    errpath = marker + ".err"
    try:
        os.unlink(marker)
    except OSError:
        pass
    code = (
        "import time, json\n"
        "t0 = time.time()\n"
        "try:\n"
        "    import jax\n"
        "    d = jax.devices()\n"
        "    out = {'ok': True, 'n': len(d), 'platform': d[0].platform,\n"
        "           'secs': round(time.time()-t0, 1)}\n"
        "except Exception as e:\n"
        "    out = {'ok': False, 'err': str(e)[:400],\n"
        "           'secs': round(time.time()-t0, 1)}\n"
        f"open({marker!r}, 'w').write(json.dumps(out))\n"
    )
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # probe the DEFAULT backend
    with open(errpath, "w") as errf:
        child = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.DEVNULL, stderr=errf,
            start_new_session=True, env=env,
        )
    deadline = time.time() + PROBE_TIMEOUT
    while time.time() < deadline:
        if os.path.exists(marker):
            time.sleep(0.5)  # let the write land
            try:
                res = json.load(open(marker))
            except Exception:  # noqa: BLE001
                time.sleep(1)
                continue
            if res.get("ok") and res.get("platform") not in ("cpu", None):
                return "tpu", res
            return "cpu", res
        if child.poll() is not None and not os.path.exists(marker):
            try:
                tail = open(errpath).read()[-400:]
            except OSError:
                tail = ""
            return "cpu", {"err": f"probe exited rc={child.returncode}: {tail}"}
        time.sleep(2)
    return "hung", {"child": child}


def wait_for_child(child):
    """A hung probe child is never killed; wait for it to exit (it holds
    a mid-claim chip client). Log hourly."""
    t0 = time.time()
    while child.poll() is None:
        waited = time.time() - t0
        if waited > 0 and int(waited) % 3600 < 5:
            log(f"hung probe child pid={child.pid} still alive after "
                f"{waited/3600:.1f}h; waiting (never kill a mid-claim client)")
        time.sleep(5)
    log(f"hung probe child pid={child.pid} exited rc={child.returncode} "
        f"after {(time.time()-t0)/60:.1f} min")


def run_capture():
    """Chip is healthy and we hold the lock: take every on-chip number
    in one claim. Returns True if BENCH_tpu.json landed.

    TPU_CAPTURE_MODE=missing runs scripts/missing_configs_recapture.py
    instead: only configs absent from (or errored in) BENCH_tpu.json
    re-run, each patching in as it lands."""
    if os.environ.get("TPU_CAPTURE_MODE") == "missing":
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.setdefault("BENCH_LOCK_SKIP", "1")
        log("capture: recapturing missing configs on the TPU backend")
        with open(os.path.join(REPO, "bench_tpu_r5.log"), "a") as blog:
            rc = subprocess.call(
                [sys.executable, "scripts/missing_configs_recapture.py"],
                cwd=REPO, env=env, stdout=blog, stderr=blog)
        log(f"capture: missing-configs recapture rc={rc}")
        return rc == 0
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["BENCH_PLATFORM"] = "default"   # probe already succeeded; go direct
    env.setdefault("BENCH_REPS", "2")   # tunnel dispatch latency is high
    env.setdefault("BENCH_LOCK_SKIP", "1")  # we already hold the chip lock
    log("capture: starting full 5-config bench on TPU backend")
    t0 = time.time()
    with open(os.path.join(REPO, "bench_tpu_r5.log"), "a") as blog:
        rc = subprocess.call(
            [sys.executable, "bench.py"], cwd=REPO, env=env,
            stdout=open(BENCH_OUT + ".tmp", "w"), stderr=blog,
            timeout=None)
    ok = False
    try:
        with open(BENCH_OUT + ".tmp") as f:
            line = f.read().strip().splitlines()[-1]
        res = json.loads(line)
        plat = res.get("extra", {}).get("platform")
        ok = rc == 0 and res.get("value", 0) > 0 and plat == "default"
        if ok:
            os.replace(BENCH_OUT + ".tmp", BENCH_OUT)
        log(f"capture: bench rc={rc} platform={plat} "
            f"value={res.get('value')} ok={ok} ({(time.time()-t0)/60:.1f} min)")
    except Exception as e:  # noqa: BLE001
        log(f"capture: bench artifact unreadable: {e!r}")
    log("capture: refreshing ops/SEGSUM_BENCH.json (i64 limb kernel)")
    with open(os.path.join(REPO, "bench_tpu_r5.log"), "a") as blog:
        rc2 = subprocess.call(
            [sys.executable, "-m", "tidb_tpu.ops.bench_segsum"],
            cwd=REPO, env=env, stdout=blog, stderr=blog)
    log(f"capture: segsum bench rc={rc2}")
    return ok


def wait_for_stray_probes():
    """A restarted watchdog must not probe while an earlier watchdog's
    hung probe child is still mid-claim (overlapping chip clients wedge
    the tunnel). Detect them by the probe-marker path embedded in their
    command line and wait, logging hourly."""
    t0 = time.time()
    while True:
        try:
            out = subprocess.run(
                ["pgrep", "-f", PROBE_DIR + "/"], capture_output=True,
                text=True).stdout.split()
        except OSError:
            return
        strays = [p for p in out if p.isdigit() and int(p) != os.getpid()]
        if not strays:
            return
        waited = time.time() - t0
        if waited < 5 or int(waited) % 3600 < 15:
            log(f"stray probe children from a previous watchdog still "
                f"alive ({','.join(strays)}); waiting before first probe")
        time.sleep(15)


def main():
    log(f"watchdog up pid={os.getpid()} interval={PROBE_INTERVAL}s "
        f"probe_timeout={PROBE_TIMEOUT}s")
    wait_for_stray_probes()
    if os.path.exists(BENCH_OUT) and \
            os.environ.get("TPU_CAPTURE_MODE") != "missing":
        log(f"{BENCH_OUT} already exists; exiting")
        return
    captures = 0
    idx = 0
    while True:
        idx += 1
        stand_down_while_handoff()
        acquire_lock(f"probe #{idx}")
        try:
            status, detail = probe_once(idx)
            if status == "hung":
                log(f"probe #{idx}: HUNG at {PROBE_TIMEOUT}s; holding lock "
                    "until the child exits")
                wait_for_child(detail["child"])
            elif status == "cpu":
                d = detail.get("err") or detail
                log(f"probe #{idx}: tpu unavailable ({str(d)[:200]})")
            elif bench_wants_chip():
                # healthy chip but the bench is waiting on the lock: the
                # bench takes its own on-chip numbers — hand it the chip
                log(f"probe #{idx}: TPU HEALTHY {detail} but bench handoff "
                    "posted — releasing the chip to the bench")
            else:
                log(f"probe #{idx}: TPU HEALTHY {detail} — claiming once")
                before = _missing_count()
                if run_capture():
                    log("capture complete; BENCH_tpu.json written. Exiting.")
                    return
                if _missing_count() < before:
                    # partial progress (a config landed before the
                    # tunnel died) — the standing recapture must keep
                    # going, not burn an attempt
                    log("capture incomplete but made progress; will re-probe")
                else:
                    captures += 1
                    if captures >= CAPTURE_ATTEMPTS:
                        log(f"capture failed {captures}x with no progress; "
                            "giving up to avoid wedging the tunnel further")
                        return
                    log("capture failed; will re-probe")
        finally:
            release_lock()
        time.sleep(PROBE_INTERVAL)


if __name__ == "__main__":
    main()
