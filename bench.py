#!/usr/bin/env python
"""Benchmark driver: TPC-H throughput on the current JAX backend.

Prints ONE json line. Headline metric is the BASELINE.json Q1 config:
  {"metric": "tpch_q1_rows_per_sec", "value": N, "unit": "rows/sec",
   "vs_baseline": R, "extra": {...}}

`extra` carries the remaining BASELINE.md configs measured this run
(Q6 range-filter, Q18 3-way join+agg, hash-join build+probe GB/s), the
platform used, and per-query sqlite cross-check status.

vs_baseline is measured against an in-process CPU SQL executor (stdlib
sqlite3) running the identical query over the identical data — the
stand-in for the reference's CPU executor, which is unavailable in this
environment (BASELINE.json ships "published": {}; see BASELINE.md).
The north-star target is >=5x the CPU executor on Q1/Q18.

Resilience: the default backend (TPU via the axon plugin here) is probed
in a SUBPROCESS with a timeout first — a hung or broken TPU init falls
back to the CPU backend instead of wedging the bench (round-1 failure
mode). Any per-metric failure is recorded in `extra` instead of killing
the artifact; a top-level failure still prints a diagnosable JSON line.

`extra` also carries the SSB Q3.2 (4-way star join) and TPC-DS Q95
(semi-join) BASELINE configs, plus (ISSUE 18) the fused TopN two-arm
microbench and the full TPC-H 22-query grid with per-query dispatch
counts and fused/classic attribution.

Env knobs: BENCH_SF (default 1.0), BENCH_SF_Q18 (default min(SF, 0.2) —
Q18's group-by cardinality is ~#orders; see extra.q18_sf for the value
used), BENCH_SF_SSB (default min(SF, 0.1)), BENCH_SF_DS (default
min(SF, 0.5)), BENCH_REPS (default 3), BENCH_CHUNK (default 2^20 rows),
BENCH_ORACLE=0 to skip sqlite baselines, BENCH_PROBE_TIMEOUT (default
300s), BENCH_PLATFORM to force a platform and skip the probe.
"""

import json
import os
import subprocess
import sys
import time
import traceback

SF = float(os.environ.get("BENCH_SF", "1.0"))
LOCK = os.environ.get("TPU_CHIP_LOCK", "/tmp/tpu_chip.lock")
HANDOFF = LOCK + ".handoff"
# long enough to outlast one full watchdog probe cycle (420s probe
# timeout + ~18 min hung-child wait observed through round 4)
LOCK_TIMEOUT = float(os.environ.get("BENCH_LOCK_TIMEOUT", "2400"))
IDLE_WAIT = float(os.environ.get("BENCH_IDLE_WAIT", "300"))
REPS = int(os.environ.get("BENCH_REPS", "3"))
CAP = int(os.environ.get("BENCH_CHUNK", str(1 << 20)))
ORACLE = os.environ.get("BENCH_ORACLE", "1") != "0"
PROBE_TIMEOUT = float(os.environ.get("BENCH_PROBE_TIMEOUT", "300"))
SF_Q18 = float(os.environ.get("BENCH_SF_Q18", str(min(SF, 0.2))))
SF_SSB = float(os.environ.get("BENCH_SF_SSB", str(min(SF, 0.1))))
SF_DS = float(os.environ.get("BENCH_SF_DS", str(min(SF, 0.5))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _lock_owner_pid():
    """(owner_line, pid or None) from the lock's owner file."""
    try:
        owner = open(os.path.join(LOCK, "owner")).read().strip()
    except OSError:
        return "?", None
    import re

    m = re.search(r"pid=(\d+)", owner)
    return owner, (int(m.group(1)) if m else None)


def chip_lock():
    """Serialize chip clients with tpu_watchdog.py via the shared mkdir
    lock: overlapping TPU clients wedge the tunnel (BASELINE.md r2).

    Round-5 discipline (VERDICT r4 weak #1): the bench NEVER "proceeds
    anyway". Protocol:
      1. drop a handoff file — the watchdog sees it and stands down
         (finishes any in-flight probe, then stops taking the lock);
      2. wait for the lock long enough to outlast one full probe cycle;
      3. a lock whose owner pid is dead is stale — break it and say so;
      4. if the lock is still held by a LIVE process at timeout, the
         bench runs CPU-only (no second TPU client is ever started) and
         the artifact says exactly that.
    Returns (status in {'acquired','skipped','unavailable'}, detail)."""
    if os.environ.get("BENCH_LOCK_SKIP") == "1":
        return "skipped", "skipped (caller holds the lock)"
    try:
        with open(HANDOFF, "w") as f:
            f.write(f"bench.py pid={os.getpid()}\n")
    except OSError:
        pass
    deadline = time.time() + LOCK_TIMEOUT
    logged = 0.0
    while True:
        try:
            os.mkdir(LOCK)
            with open(os.path.join(LOCK, "owner"), "w") as f:
                f.write(f"bench.py pid={os.getpid()}\n")
            return "acquired", "acquired"
        except FileExistsError:
            owner, pid = _lock_owner_pid()
            if pid is not None and not os.path.exists(f"/proc/{pid}"):
                # break the stale lock ATOMICALLY: rename wins or loses
                # as a unit, so two waiters can't both dismantle it and
                # a fresh lock taken in between is never clobbered
                grave = f"{LOCK}.stale.{os.getpid()}.{int(time.time())}"
                try:
                    os.rename(LOCK, grave)
                    log(f"# broke stale chip lock (owner '{owner}' pid "
                        f"{pid} is dead)")
                    import shutil

                    shutil.rmtree(grave, ignore_errors=True)
                except OSError:
                    pass  # someone else broke/retook it; retry normally
            if time.time() > deadline:
                try:
                    os.unlink(HANDOFF)  # stop blocking watchdog probes
                except OSError:
                    pass
                return "unavailable", (
                    f"unavailable: lock held by live '{owner}' after "
                    f"{LOCK_TIMEOUT}s wait; benching CPU-only — no TPU "
                    "client started")
            if time.time() - logged > 60:
                logged = time.time()
                log(f"# waiting on chip lock (held by: {owner}; handoff "
                    "posted; watchdog will stand down)")
            time.sleep(2)


def chip_unlock(status):
    try:
        os.unlink(HANDOFF)
    except OSError:
        pass
    if status != "acquired":
        return
    for fn in (lambda: os.unlink(os.path.join(LOCK, "owner")),
               lambda: os.rmdir(LOCK)):
        try:
            fn()
        except OSError:
            pass


def pick_platform():
    """Probe the default jax backend in a DETACHED subprocess; fall back
    to cpu without ever killing the probe.

    Round 1's bench died inside TPU backend init; round 2's tunnel
    re-wedged when timed-out probe children were KILLED mid-claim (the
    documented wedge trigger, BASELINE.md). So the probe child is fully
    detached and simply abandoned on timeout: it either finishes its
    claim cleanly and exits, or keeps waiting harmlessly — the bench
    meanwhile proceeds on CPU and says so in the artifact.
    """
    forced = os.environ.get("BENCH_PLATFORM")
    if forced:
        return forced, f"forced via BENCH_PLATFORM={forced}"
    import tempfile

    fd, marker = tempfile.mkstemp(prefix="bench_probe_")
    os.close(fd)
    os.unlink(marker)  # the child re-creates it on success
    errpath = marker + ".err"
    code = (
        "import jax, json\n"
        "d = jax.devices()\n"
        "open(%r, 'w').write(json.dumps([len(d), d[0].platform]))\n" % marker
    )
    with open(errpath, "w") as errf:
        child = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.DEVNULL, stderr=errf,
            start_new_session=True,  # survives us; nobody ever kills it
        )

    def err_tail():
        try:
            with open(errpath) as f:
                return f.read()[-1500:]
        except OSError:
            return ""

    deadline = time.time() + PROBE_TIMEOUT
    try:
        while time.time() < deadline:
            if os.path.exists(marker):
                try:
                    n, plat = json.load(open(marker))
                    os.unlink(marker)
                    return "default", f"OK {n} {plat}"
                except Exception:  # noqa: BLE001  (partial write: retry)
                    pass
            if child.poll() is not None and not os.path.exists(marker):
                return ("cpu", f"backend probe exited rc={child.returncode}: "
                        f"{err_tail()}")
            time.sleep(1)
        log("# backend probe still claiming at timeout; leaving it to finish "
            "(never kill a mid-claim client) and benching on CPU")
        return "cpu", f"backend probe timed out after {PROBE_TIMEOUT}s (not killed)"
    finally:
        # the abandoned child may still create the marker later; leave
        # only bounded residue (single .err file reused next run is fine)
        if child.poll() is not None:
            for pth in (marker, errpath):
                try:
                    os.unlink(pth)
                except OSError:
                    pass


def machine_load(sample_s=0.25):
    """Snapshot of everything that could invalidate a measurement:
    1/5/15-min load averages plus any OTHER python/compile process
    CURRENTLY burning >50% of a core — measured as a CPU-time rate over
    a short two-sample window, not cumulative seconds (a long-lived but
    idle daemon must not read as busy). Recorded into the artifact
    before and after each config so a perturbed number is visibly
    perturbed (round-3 lesson: the headline moved -38% with no load
    evidence either way)."""
    snap = {"loadavg": [round(x, 2) for x in os.getloadavg()]}

    def cpu_sample():
        out = {}
        me = os.getpid()
        tck = os.sysconf("SC_CLK_TCK")
        for pid in os.listdir("/proc"):
            if not pid.isdigit() or int(pid) == me:
                continue
            try:
                with open(f"/proc/{pid}/stat") as f:
                    parts = f.read().split()
                cpu_s = (int(parts[13]) + int(parts[14])) / tck
                with open(f"/proc/{pid}/cmdline") as f:
                    cmd = f.read().replace("\x00", " ").strip()
            except (OSError, IndexError, ValueError):
                continue
            if any(k in cmd for k in ("python", "pytest", "cc1plus",
                                      "clang", "ninja", "node")):
                out[pid] = (cpu_s, cmd)
        return out

    try:
        first = cpu_sample()
        time.sleep(sample_s)
        busy = []
        for pid, (c1, cmd) in cpu_sample().items():
            c0 = first.get(pid)
            if c0 is None:
                continue
            rate = (c1 - c0[0]) / sample_s
            if rate > 0.5:
                busy.append(f"pid{pid}:{rate:.1f}cores:{cmd[:60]}")
        snap["busy_procs"] = busy[:8]
    except OSError:
        pass
    return snap


def wait_for_idle(tag=None, extra=None, max_wait=IDLE_WAIT):
    """Block until the machine is measurably idle before a config runs
    (VERDICT r4 weak #1: never record a headline while contended).

    Primary criterion: 1-min loadavg < 0.3. Shortcut: after 90 s, three
    consecutive samples with no OTHER busy process and loadavg < 0.6
    also count as idle (our own just-finished work keeps the decaying
    loadavg above 0.3 for ~a minute with nothing actually running).
    Records what it saw either way; returns True if idle was reached."""
    t0 = time.time()
    calm = 0
    how = "gave_up"
    while True:
        snap = machine_load()
        la1 = snap["loadavg"][0]
        busy = snap.get("busy_procs", [])
        calm = calm + 1 if (not busy and la1 < 0.6) else 0
        waited = time.time() - t0
        if la1 < 0.3:
            how = "loadavg"
            break
        if calm >= 3 and waited >= 90:
            how = "calm"
            break
        if waited > max_wait:
            log(f"# idle-wait gave up after {max_wait}s: loadavg={la1} "
                f"busy={busy[:2]}")
            break
        time.sleep(5)
    idle = how != "gave_up"
    if extra is not None and tag:
        extra[f"{tag}_idle_wait"] = {
            "waited_s": round(time.time() - t0, 1), "idle": idle,
            "criterion": how, "loadavg": snap["loadavg"],
            "busy_procs": busy[:4]}
    return idle


def bench_provenance():
    """Provenance stamped into every bench JSON artifact (ISSUE 16):
    the git revision the numbers were measured at plus the engaged
    feature flags (their default values in this tree — every bench
    session runs with defaults). perf_check warns when a committed
    floor's revision differs from the tree being checked, so a stale
    capture can't silently gate a changed engine."""
    rev = ""
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5).stdout.strip()
    except Exception:  # noqa: BLE001 — provenance is best-effort
        pass
    flags = {}
    try:
        from tidb_tpu.session.sysvars import SysVarStore

        sv = SysVarStore({})  # defaults only — bench sessions run stock
        for name in ("tidb_enable_tpu_exec", "tidb_device_engine_mode",
                     "tidb_tpu_pipeline_fuse", "tidb_tpu_columnar_enable",
                     "tidb_tpu_plan_feedback", "tidb_tpu_join_probe_mode",
                     "tidb_tpu_stage_encoded",
                     "tidb_tpu_device_buffer_cache_bytes"):
            try:
                flags[name] = sv.get(name)
            except Exception:  # noqa: BLE001 — a renamed flag drops out
                pass
    except Exception:  # noqa: BLE001
        pass
    return {"git_rev": rev, "flags": flags}


def bench_query(s, engine_sql, sqlite_conn, sqlite_sql, rows, reps=REPS,
                ordered=True, extra=None, tag=None):
    """Run engine_sql reps times; cross-check once vs sqlite. Returns
    (rows_per_sec, vs_sqlite, best_s, check). With extra/tag, waits for
    machine idleness and records load snapshots around the measurement
    into the artifact."""
    from tidb_tpu.testutil import rows_equal

    from tidb_tpu.utils import dispatch as _dsp
    from tidb_tpu.utils import metrics as _M

    def engine_dispatches():
        # the ENGINE-reported figure: the process-global dispatch
        # counter the engine itself maintains (rendered on /metrics)
        return int(sum(v for _lbl, v in _M.DISPATCH_TOTAL.samples()))

    if extra is not None and tag:
        wait_for_idle(tag, extra)
        extra[f"{tag}_load_before"] = machine_load()
    t0 = time.perf_counter()
    got = s.query(engine_sql)  # compile + warmup
    warm = time.perf_counter() - t0
    best = float("inf")
    d0 = engine_dispatches()
    d0_local = _dsp.count()
    for _ in range(reps):
        d0 = engine_dispatches()
        d0_local = _dsp.count()
        t0 = time.perf_counter()
        got = s.query(engine_sql)
        best = min(best, time.perf_counter() - t0)
    if extra is not None and tag:
        # device round trips of the last exec: the tunnel pays ~0.5 s
        # per dispatch, so this is the latency floor in one number.
        # Headline figure comes from the engine metric; the bench's own
        # thread-local count stays as a cross-check that fails loudly
        # (the bench is the only engine thread, so they must agree)
        eng = engine_dispatches() - d0
        local = _dsp.count() - d0_local
        extra[f"{tag}_dispatches"] = eng
        if eng != local:
            extra[f"{tag}_dispatch_crosscheck"] = (
                f"MISMATCH: engine metric says {eng}, bench-local "
                f"dispatch count says {local}")
            log(f"# DISPATCH CROSS-CHECK MISMATCH ({tag}): "
                f"engine={eng} local={local}")
    vs, check, cpu_s = 0.0, "skipped", None
    if sqlite_conn is not None:
        cpu_s = float("inf")
        for _ in range(max(1, reps - 1)):
            t0 = time.perf_counter()
            want = sqlite_conn.execute(sqlite_sql).fetchall()
            cpu_s = min(cpu_s, time.perf_counter() - t0)
        ok, msg = rows_equal(got, want, ordered=ordered)
        check = "ok" if ok else f"MISMATCH: {msg}"
        vs = cpu_s / best
    if extra is not None and tag:
        extra[f"{tag}_load_after"] = machine_load()
    log(f"#   warm={warm:.2f}s best={best * 1e3:.1f}ms"
        + (f" sqlite={cpu_s * 1e3:.1f}ms" if cpu_s else "") + f" check={check}")
    return rows / best, vs, best, check


# --- pre-PR3 join baseline block (CPU backend, local engine) ---------------
# Measured on the seed engine immediately before the partitioned device
# join overhaul (ISSUE 3): local session, 50k-row build x 400k-row probe,
# count+sum probe query, best-of-3 warm on an idle machine:
#   warm_best = 0.500 s  ->  join_build_probe_gbps = 0.014
# (the per-query XLA retrace of the probe/expand closures plus the
# host np.argsort build round trip dominated). The ISSUE 3 acceptance
# gate is >= 5x this number with 0 warm recompiles.
JOIN_MICRO_BASELINE_GBPS_CPU = 0.014
# largest (the baseline-block config) FIRST: a prior config's freed
# working set measurably perturbs whoever runs after it, and the
# headline number must not absorb that
JOIN_MICRO_GRID = [(50_000, 400_000), (10_000, 100_000)]


def bench_join_micro(extra=None):
    """Join microbench (ISSUE 3): build-rows x probe-rows grid, cold vs
    warm, on the LOCAL engine (the HashJoinExec the partitioned-join
    overhaul rebuilt). Loud cross-checks: every config's rows must match
    the sqlite oracle exactly (count AND a content hash), and the
    engine-reported JOIN_COMPILE_TOTAL must not move across warm runs —
    a shape key leaking into traced code fails here before it regresses
    a real workload."""
    import numpy as np

    from tidb_tpu.session import Session
    from tidb_tpu.storage.catalog import Catalog
    from tidb_tpu.testutil import mirror_to_sqlite, rows_equal
    from tidb_tpu.utils import metrics as _M

    def compiles():
        return int(sum(v for _, v in _M.JOIN_COMPILE_TOTAL.samples()))

    out = {"configs": [], "baseline_gbps": JOIN_MICRO_BASELINE_GBPS_CPU}
    rng = np.random.default_rng(11)
    for nb, npr in JOIN_MICRO_GRID:
        s = Session(catalog=Catalog(), chunk_capacity=1 << 17)
        s.execute("SET tidb_slow_log_threshold = 300000")
        s.execute("create table b (k bigint, v bigint)")
        s.execute("create table p (k bigint, w bigint)")
        s.catalog.table("test", "b").insert_columns(
            {"k": rng.integers(0, nb, nb), "v": np.arange(nb)})
        s.catalog.table("test", "p").insert_columns(
            {"k": rng.integers(0, nb, npr), "w": np.arange(npr)})
        oracle = mirror_to_sqlite(s.catalog, tables=["b", "p"])
        # timed config: IDENTICAL query to the pre-PR baseline block
        q = ("select count(*) as n, sum(p.w) as sw "
             "from p join b on p.k = b.k")
        # oracle config: adds the build payload so the cross-check also
        # covers build-side gather content, not just match cardinality
        q_check = ("select count(*) as n, sum(p.w) as sw, sum(b.v) as sv "
                   "from p join b on p.k = b.k")
        t0 = time.perf_counter()
        got = s.query(q)
        cold = time.perf_counter() - t0
        s.query(q)  # steady the plan (auto-analyze may land stats once)
        best = float("inf")
        c0 = compiles()
        for _ in range(3):
            t0 = time.perf_counter()
            got = s.query(q)
            best = min(best, time.perf_counter() - t0)
        recompiles = compiles() - c0
        ok, msg = rows_equal(got, oracle.execute(q).fetchall(),
                             ordered=False)
        if ok:
            got = s.query(q_check)
            want = oracle.execute(q_check).fetchall()
            ok, msg = rows_equal(got, want, ordered=False)
        else:
            want = []
        check = "ok" if ok else f"MISMATCH: {msg}"
        # result-hash equality: the whole aggregate tuple, not just the
        # row count, must agree with the oracle
        import hashlib

        def rhash(rows):
            return hashlib.sha256(repr(sorted(map(tuple, rows)))
                                  .encode()).hexdigest()[:16]
        hash_equal = rhash(got) == rhash(want)
        jbytes = npr * 2 * 8 + nb * 2 * 8
        cfg = {
            "build_rows": nb, "probe_rows": npr,
            "cold_s": round(cold, 4), "warm_best_s": round(best, 4),
            "warm_over_cold": round(cold / max(best, 1e-9), 2),
            "gbps": round(jbytes / best / 1e9, 4),
            "warm_recompiles": recompiles,
            "check": check, "hash_equal": hash_equal,
        }
        if recompiles != 0:
            cfg["recompile_crosscheck"] = (
                f"MISMATCH: JOIN_COMPILE_TOTAL moved by {recompiles} "
                "across warm runs (shape key leaked into traced code)")
            log(f"# JOIN RETRACE ({nb}x{npr}): {recompiles} warm recompiles")
        if not ok or not hash_equal:
            log(f"# JOIN ORACLE MISMATCH ({nb}x{npr}): {check}")
        out["configs"].append(cfg)
        log(f"# join {nb}x{npr}: cold={cold:.3f}s warm={best:.3f}s "
            f"gbps={cfg['gbps']} recompiles={recompiles} check={check}")
        # drop this config's working set before the next one measures:
        # a lingering session + sqlite mirror measurably perturbs the
        # following config's numpy paths (page-cache pressure)
        import gc

        oracle.close()
        s = oracle = got = want = None
        gc.collect()
    head = out["configs"][0]  # the baseline-block config (50k x 400k)
    out["gbps"] = head["gbps"]
    out["improvement_vs_baseline"] = round(
        head["gbps"] / JOIN_MICRO_BASELINE_GBPS_CPU, 2)
    return out


def bench_plan_cache(extra):
    """Plan-cache microbench: repeated point-SELECT and prepared-execute
    loops, statements/sec cold (cache off / first-touch) vs warm
    (cache-hit), plus the ENGINE-reported hit rate cross-checked loudly
    against the loop's own accounting (the PR-1 dispatch-cross-check
    pattern: the engine metric is the headline, the bench's local figure
    must agree or the artifact says so)."""
    from tidb_tpu.session import Session
    from tidb_tpu.storage.catalog import Catalog
    from tidb_tpu.utils import metrics as _M

    n_rows, n_iter = 1000, 400
    s = Session(catalog=Catalog())
    s.execute("SET tidb_slow_log_threshold = 300000")
    # an OLTP-realistic row: wide schema, secondary indexes, fresh
    # stats — planning cost reflects real access-path selection, not a
    # two-column toy
    s.execute("CREATE TABLE pcb (id bigint, k bigint,"
              " a bigint, b bigint, c bigint, d bigint, e bigint,"
              " f bigint, primary key (id, k))")
    s.execute("CREATE INDEX pcb_k ON pcb (k)")
    s.execute("CREATE INDEX pcb_ab ON pcb (a, b)")
    s.execute("INSERT INTO pcb VALUES "
              + ",".join(f"({i},{i % 97},{i % 11},{i % 13},{i * 2},"
                         f"{i * 3},{i * 5},{i * 7})" for i in range(n_rows)))
    s.execute("ANALYZE TABLE pcb")
    # sysbench-style composite-key point read: access-path selection
    # works over three indexes, the probe pins both key columns
    point = "select c, d, e, f from pcb where id = %d and k = %d"
    out = {"iters": n_iter}

    def args(i):
        return i % n_rows, (i % n_rows) % 97

    def loop_text(n):
        t0 = time.perf_counter()
        for i in range(n):
            s.query(point % args(i))
        return n / (time.perf_counter() - t0)

    def loop_prepared(sid, n):
        t0 = time.perf_counter()
        for i in range(n):
            s.execute_prepared(sid, list(args(i)))
        return n / (time.perf_counter() - t0)

    # cold: full parse+plan per statement (non-prepared cache is off by
    # default, so this is the engine's pre-cache statement path)
    s.query(point % args(0))  # jit warmup out of band
    out["cold_stmts_per_sec"] = round(loop_text(n_iter), 1)

    # warm prepared: one fill execution, then the loop runs on cache hits
    sid, _ = s.prepare(
        "select c, d, e, f from pcb where id = ? and k = ?")
    s.execute_prepared(sid, list(args(0)))  # fill (miss pays the verify)
    h0 = s.catalog.plan_cache.hits
    m0 = _M.PLAN_CACHE_TOTAL.value(event="hit")
    out["warm_prepared_stmts_per_sec"] = round(loop_prepared(sid, n_iter), 1)
    eng_hits = _M.PLAN_CACHE_TOTAL.value(event="hit") - m0
    local_hits = s.catalog.plan_cache.hits - h0
    out["hit_rate"] = round(eng_hits / n_iter, 4)
    if eng_hits != local_hits:
        out["hit_crosscheck"] = (
            f"MISMATCH: engine metric says {eng_hits}, cache-object "
            f"accounting says {local_hits}")
        log(f"# PLAN-CACHE CROSS-CHECK MISMATCH: metric={eng_hits} "
            f"cache={local_hits}")
    # the summary table must tell the same story per digest
    rows = s.query(
        "select exec_count, plan_cache_hits from"
        " information_schema.statements_summary where digest_text ="
        " 'select c , d , e , f from pcb where id = ? and k = ?'")
    summ_hits = rows[0][1] if rows else -1
    if rows and summ_hits != local_hits:
        out["summary_crosscheck"] = (
            f"MISMATCH: statements_summary says {summ_hits}, cache "
            f"says {local_hits}")
        log(f"# PLAN-CACHE SUMMARY CROSS-CHECK MISMATCH: "
            f"summary={summ_hits} cache={local_hits}")

    # warm non-prepared: text statements through the opt-in cache
    s.execute("SET tidb_enable_non_prepared_plan_cache = 1")
    s.query(point % args(0))  # fill
    out["warm_text_stmts_per_sec"] = round(loop_text(n_iter), 1)
    s.execute("SET tidb_enable_non_prepared_plan_cache = 0")

    out["warm_over_cold"] = round(
        out["warm_prepared_stmts_per_sec"]
        / max(out["cold_stmts_per_sec"], 1e-9), 3)
    log(f"# plan cache: cold={out['cold_stmts_per_sec']}/s warm_prep="
        f"{out['warm_prepared_stmts_per_sec']}/s warm_text="
        f"{out['warm_text_stmts_per_sec']}/s hit_rate={out['hit_rate']}")
    return out


def bench_multichip(extra=None, n_rows=None, reps=None,
                    write_path="MULTICHIP_r06.json"):
    """Sharded scale-out capture (ISSUE 13): the SAME scan-agg query at
    1 -> 2 -> 4 workers over SHARD BY placement, interleaved arms,
    serial-oracle hash equality on every arm.

    Metric semantics on a single-core harness (this box has 1 CPU):
    workers are in-process, so raw wall clock CANNOT scale — what a
    multi-host fleet achieves is the distributed CRITICAL PATH, which
    IS measurable here: each owner's partial is timed individually
    (sequentially, so measurements don't contend), and

        scaleout_s = max(partial_i) + (wall - sum(partial_i))

    i.e. the slowest owner's partial plus the measured coordinator
    overhead (rewrite + drain + final merge) from the real end-to-end
    run. At W=1 that degenerates to the measured wall clock, so
    speedups are self-relative. On a >=4-core box the raw wall-clock
    speedup is reported alongside and should approach the modeled one.
    Every arm's full result must hash-equal the serial oracle's."""
    import hashlib
    import threading as _threading

    import numpy as np

    from tidb_tpu.parallel.dcn import Cluster, Worker, partial_rewrite
    from tidb_tpu.session import Session

    n_rows = n_rows or int(os.environ.get("BENCH_MULTICHIP_ROWS",
                                          str(1 << 20)))
    reps = reps or max(REPS, 3)
    rng = np.random.default_rng(13)
    k = rng.permutation(n_rows).astype(np.int64)
    g = (k % 97).astype(np.int64)
    v = (k * 7 - 3).astype(np.int64)
    ddl = ("create table t (k bigint, g bigint, v bigint) "
           "shard by hash(k) shards 8")
    sql = ("select g, count(*) as n, sum(v) as sv, min(v) as mv, "
           "max(v) as xv from t group by g order by g")

    def rows_hash(rows):
        return hashlib.sha256(
            repr([tuple(int(x) for x in r) for r in rows]).encode()
        ).hexdigest()[:16]

    oracle = Session(chunk_capacity=CAP)
    oracle.execute(ddl)
    oracle.catalog.table("test", "t").insert_columns(
        {"k": k, "g": g, "v": v})
    want_hash = rows_hash(oracle.query(sql))

    fleets = {}
    for W in (1, 2, 4):
        ws = [Worker() for _ in range(W)]
        for w in ws:
            _threading.Thread(target=w.serve_forever, daemon=True).start()
        cl = Cluster([("127.0.0.1", w.port) for w in ws],
                     rpc_timeout_s=600.0)
        cl.ddl(ddl)
        cl.load_sharded("t", arrays={"k": k, "g": g, "v": v})
        fleets[W] = (ws, cl)

    partial_sql, _final, _names = partial_rewrite(
        sql, partitioned=frozenset({"t"}))
    out = {"n_rows": n_rows, "reps": reps, "host_cpus": os.cpu_count(),
           "oracle_hash": want_hash, "arms": {}}
    best = {}  # W -> (scaleout_s, wall_s, max_partial_s)
    try:
        # warm every arm (compile + plan caches) and pin hash equality
        for W, (ws, cl) in fleets.items():
            h = rows_hash(cl.query(sql))
            out["arms"][W] = {"workers": W, "hash_equal": h == want_hash,
                              "hash": h}
        # interleaved measurement: rep-major, arm-minor, so machine
        # drift perturbs every arm equally instead of biasing one
        for _rep in range(reps):
            for W, (ws, cl) in fleets.items():
                t0 = time.perf_counter()
                cl.query(sql)
                wall = time.perf_counter() - t0
                pt = []
                for i in range(W):
                    t0 = time.perf_counter()
                    first = cl._call(i, {"cmd": "partial_paged",
                                         "sql": partial_sql,
                                         "page_rows": 1 << 16})
                    cl._drain_pages(i, first)
                    pt.append(time.perf_counter() - t0)
                scaleout = max(pt) + max(wall - sum(pt), 0.0)
                cur = best.get(W)
                if cur is None or scaleout < cur[0]:
                    best[W] = (scaleout, wall, max(pt))
        for W, (scaleout, wall, mp) in best.items():
            out["arms"][W].update(
                scaleout_s=round(scaleout, 4), wall_s=round(wall, 4),
                max_partial_s=round(mp, 4),
                rows_per_sec_scaleout=round(n_rows / scaleout, 1))
        base = best[1][0]
        out["speedup_2w"] = round(base / best[2][0], 3)
        out["speedup_4w"] = round(base / best[4][0], 3)
        out["wall_speedup_4w"] = round(best[1][1] / best[4][1], 3)
        out["hash_equal"] = all(a["hash_equal"]
                                for a in out["arms"].values())
        out["arms"] = {str(W): a for W, a in out["arms"].items()}
    finally:
        for _W, (_ws, cl) in fleets.items():
            try:
                cl.shutdown()
            except Exception:  # noqa: BLE001 — bench cleanup
                pass
    out["provenance"] = bench_provenance()
    if write_path:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            write_path)
        json.dump(out, open(path, "w"), indent=1)
    if extra is not None:
        extra["multichip"] = {kk: out[kk] for kk in
                              ("speedup_2w", "speedup_4w",
                               "wall_speedup_4w", "hash_equal",
                               "host_cpus")}
    log(f"# multichip: speedup_2w={out.get('speedup_2w')} "
        f"speedup_4w={out.get('speedup_4w')} "
        f"wall_4w={out.get('wall_speedup_4w')} "
        f"hash_equal={out.get('hash_equal')}")
    return out


def bench_elastic(extra=None, n_rows=None, before_s=1.5, after_s=1.5,
                  n_readers=2, n_writers=2):
    """Elastic-topology SLO bench (ISSUE 19): p99 latency + throughput
    dip DURING a live online reshard under sustained mixed traffic.
    Readers (group-agg over the stable keyspace, sqlite-oracle-checked
    on EVERY result) and 2PC point-insert writers run continuously
    against a 3-worker fleet; mid-run the table reshards 12 -> 24
    shards (shard-function change: every shard moves — the worst
    case). Captured: per-phase read p50/p99 (before/during/after the
    reshard), statements served per 1-second window, and the
    throughput dip (served rate during / before). The serving SLO —
    every 1s window serves at least one successful statement, and
    every acked writer row survives the cutover — is what perf_check
    floors; the latency numbers are the operator-facing artifact."""
    import threading as _threading

    import numpy as np

    from tidb_tpu.errors import TiDBTPUError
    from tidb_tpu.parallel.dcn import Cluster, Worker
    from tidb_tpu.session import Session
    from tidb_tpu.testutil import mirror_to_sqlite, rows_equal

    n_rows = n_rows or int(os.environ.get("BENCH_ELASTIC_ROWS",
                                          str(1 << 16)))
    rng = np.random.default_rng(19)
    k = rng.permutation(n_rows).astype(np.int64)
    g = (k % 23).astype(np.int64)
    v = (k * 5 - 7).astype(np.int64)
    ddl = ("create table e (k bigint, g bigint, v bigint) "
           "shard by hash(k) shards 12")
    read_sql = (f"select g, count(*) as n, sum(v) as sv from e "
                f"where k < {n_rows} group by g order by g")

    oracle = Session(chunk_capacity=CAP)
    oracle.execute(ddl)
    oracle.catalog.table("test", "e").insert_columns(
        {"k": k, "g": g, "v": v})
    conn = mirror_to_sqlite(oracle.catalog)
    want = conn.execute(read_sql).fetchall()

    workers = [Worker() for _ in range(3)]
    for w in workers:
        _threading.Thread(target=w.serve_forever, daemon=True).start()
    cl = Cluster([("127.0.0.1", w.port) for w in workers],
                 rpc_timeout_s=600.0)
    cl.ddl(ddl)
    cl.load_sharded("e", arrays={"k": k, "g": g, "v": v})

    stop = _threading.Event()
    lock = _threading.Lock()
    reads = []       # (t_done, dur_s) of oracle-exact reads
    writes = []      # (t_done, dur_s) of acked inserts
    mismatches = []  # correctness violations — must stay empty
    errors = []      # non-transient typed errors — must stay empty
    applied = []     # acked writer sql, replayed into the oracle

    def transient(e):
        # a statement landing inside a 2PC prepare->commit window is
        # refused typed and retried by the client — the documented
        # guard, topology change or not
        return "pending" in str(e)

    def reader():
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                got = cl.query(read_sql)
            except TiDBTPUError as e:
                if not transient(e):
                    with lock:
                        errors.append(repr(e))
                continue
            t1 = time.perf_counter()
            ok, msg = rows_equal(got, want, ordered=True)
            with lock:
                (reads.append((t1, t1 - t0)) if ok
                 else mismatches.append(msg))

    def writer(wid):
        nn = 0
        while not stop.is_set():
            kk = n_rows + wid * 10_000_000 + nn
            nn += 1
            sql = (f"insert into e (k, g, v) values "
                   f"({kk}, {kk % 23}, {kk * 5})")
            t0 = time.perf_counter()
            try:
                cl.execute_dml(sql)
            except TiDBTPUError as e:
                if not transient(e):
                    with lock:
                        errors.append(repr(e))
                continue
            t1 = time.perf_counter()
            with lock:
                writes.append((t1, t1 - t0))
                applied.append(sql)
            time.sleep(0.002)

    threads = ([_threading.Thread(target=reader)
                for _ in range(n_readers)]
               + [_threading.Thread(target=writer, args=(w,))
                  for w in range(n_writers)])
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    try:
        time.sleep(before_s)
        t_r0 = time.perf_counter()
        cl.reshard("alter table e shard by hash(k) shards 24")
        t_r1 = time.perf_counter()
        time.sleep(after_s)
    finally:
        stop.set()
        for t in threads:
            t.join(120)
    t_end = time.perf_counter()
    try:
        # every acked writer row must have survived the cutover: replay
        # the acked multiset into the oracle, compare the WHOLE table
        for sql in applied:
            conn.execute(sql)
        full = "select count(*) as n, sum(v) as sv from e"
        okf, msgf = rows_equal(cl.query(full),
                               conn.execute(full).fetchall())
        new_shards = cl.placement("e").shards
    finally:
        try:
            cl.shutdown()
        except Exception:  # noqa: BLE001 — bench cleanup
            pass
        conn.close()
    check = "ok"
    if errors:
        check = f"TYPED ERRORS ({len(errors)}): {errors[0]}"[:300]
    if mismatches:
        check = f"READ MISMATCH: {mismatches[0]}"[:300]
    if not okf:
        check = f"WRITER ROWS LOST: {msgf}"[:300]
    if new_shards != 24:
        check = f"RESHARD DID NOT LAND: shards={new_shards}"

    stamps = sorted(t for t, _d in reads + writes)
    windows = []
    w0 = t_start
    while w0 < t_end:
        windows.append(sum(1 for t in stamps if w0 <= t < w0 + 1.0))
        w0 += 1.0

    def pctl(durs, q):
        if not durs:
            return None
        ds = sorted(durs)
        return round(ds[min(len(ds) - 1, int(q * len(ds)))] * 1e3, 2)

    phases = {"before": [d for t, d in reads if t < t_r0],
              "during": [d for t, d in reads if t_r0 <= t < t_r1],
              "after": [d for t, d in reads if t >= t_r1]}
    n_before = sum(1 for t in stamps if t < t_r0)
    n_during = sum(1 for t in stamps if t_r0 <= t < t_r1)
    rate_before = n_before / max(t_r0 - t_start, 1e-9)
    rate_during = n_during / max(t_r1 - t_r0, 1e-9)
    out = {
        "n_rows": n_rows, "workers": 3, "shards": "12 -> 24",
        "reshard_s": round(t_r1 - t_r0, 3),
        "wall_s": round(t_end - t_start, 3),
        "stmts_served": len(stamps),
        "reads_ok": len(reads), "writes_acked": len(writes),
        "windows_1s": windows,
        "served_every_window": all(c > 0 for c in windows),
        "read_p50_ms": {p: pctl(d, 0.50) for p, d in phases.items()},
        "read_p99_ms": {p: pctl(d, 0.99) for p, d in phases.items()},
        "rate_before_sps": round(rate_before, 1),
        "rate_during_sps": round(rate_during, 1),
        "throughput_dip": round(rate_during / max(rate_before, 1e-9), 3),
        "check": check,
        "provenance": bench_provenance(),
    }
    log(f"# elastic: reshard={out['reshard_s']}s of {out['wall_s']}s, "
        f"{out['stmts_served']} stmts, dip={out['throughput_dip']} "
        f"p99 before/during/after="
        f"{out['read_p99_ms']['before']}/{out['read_p99_ms']['during']}/"
        f"{out['read_p99_ms']['after']}ms "
        f"served_every_window={out['served_every_window']} "
        f"check={check}")
    if extra is not None:
        extra["elastic"] = {kk: out[kk] for kk in (
            "reshard_s", "served_every_window", "throughput_dip",
            "read_p99_ms", "stmts_served", "check")}
    return out


def bench_oltp(extra, clients_list=(8, 16), iters=150):
    """Multi-client OLTP benchmark (ISSUE 7): sysbench-style point-get
    workload at N client threads through the serving tier, coalesced
    (gather window on) vs unbatched (window=0 — every statement runs
    singleton through the same scheduler), reporting stmts/s, p99,
    engine batch/admission counters, the plan-cache hit rate, and a
    serial-oracle byte-identical cross-check of every statement's
    result. A small update mix rides along (reported, not floored)."""
    import threading

    from tidb_tpu.serving import StatementScheduler
    from tidb_tpu.session import Session
    from tidb_tpu.storage.catalog import Catalog
    from tidb_tpu.utils import metrics as _M

    n_rows = 5000
    cat = Catalog()
    boot = Session(catalog=cat)
    boot.execute("SET GLOBAL tidb_slow_log_threshold = 300000")
    boot.execute("SET GLOBAL tidb_trace_sample_rate = 0")
    boot.execute("CREATE TABLE sbtest (id bigint primary key, k bigint,"
                 " c varchar(64), pad varchar(32))")
    boot.execute("INSERT INTO sbtest VALUES " + ",".join(
        f"({i},{i % 499},'c-{i:010d}-{i * 7 % 997:04d}','pad-{i % 83}')"
        for i in range(n_rows)))
    boot.execute("ANALYZE TABLE sbtest")
    point_tmpl = "select c, pad, k from sbtest where id = ?"

    def key_of(client, i):
        return (client * 7919 + i * 97) % n_rows

    def run_config(n_clients, window_us, collect=None):
        """One (clients, window) config; returns (stmts/s, p99_ms)."""
        boot.execute(f"SET GLOBAL tidb_tpu_batch_window_us = {window_us}")
        sched = StatementScheduler(cat, workers=4)
        sessions = [Session(catalog=cat) for _ in range(n_clients)]
        sids = [s.prepare(point_tmpl)[0] for s in sessions]
        # fill + per-session warm (the miss pays sentinel verification)
        sched.submit_prepared(sessions[0], sids[0], [0])
        lats = [[] for _ in range(n_clients)]
        barrier = threading.Barrier(n_clients + 1)

        def client(ci):
            sess, sid = sessions[ci], sids[ci]
            barrier.wait()
            for i in range(iters):
                t0 = time.perf_counter()
                rs = sched.submit_prepared(sess, sid, [key_of(ci, i)])
                lats[ci].append(time.perf_counter() - t0)
                if collect is not None:
                    collect[ci].append(rs.rows)

        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(n_clients)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        sched.shutdown()
        flat = sorted(x for l in lats for x in l)
        p99 = flat[int(len(flat) * 0.99) - 1] if flat else 0.0
        return n_clients * iters / wall, p99 * 1e3

    out = {"iters": iters, "rows": n_rows, "configs": []}
    for n_clients in clients_list:
        h0 = _M.PLAN_CACHE_TOTAL.value(event="hit")
        cold_rps, cold_p99 = run_config(n_clients, 0)
        bat_collect = [[] for _ in range(n_clients)]
        c0 = _M.BATCH_COALESCE_TOTAL.value()
        hist0 = list(next(
            (c for _l, c, _s, _e in _M.BATCH_SIZE.samples()), [])) or None
        warm_rps, warm_p99 = run_config(n_clients, 1500, collect=bat_collect)
        hits = _M.PLAN_CACHE_TOTAL.value(event="hit") - h0
        total_stmts = 2 * n_clients * iters + 2  # + the two fills
        hist1 = list(next(
            (c for _l, c, _s, _e in _M.BATCH_SIZE.samples()), []))
        hist = (hist1 if hist0 is None
                else [a - b for a, b in zip(hist1, hist0)])
        # oracle: the same statements serially, compared byte-identical
        oracle = Session(catalog=cat)
        osid, _ = oracle.prepare(point_tmpl)
        mismatches = 0
        for ci in range(n_clients):
            for i, got in enumerate(bat_collect[ci]):
                want = oracle.execute_prepared(osid, [key_of(ci, i)]).rows
                if repr(got) != repr(want):
                    mismatches += 1
        cfg = {
            "clients": n_clients,
            "unbatched_stmts_per_sec": round(cold_rps, 1),
            "batched_stmts_per_sec": round(warm_rps, 1),
            "speedup": round(warm_rps / max(cold_rps, 1e-9), 3),
            "p99_ms_unbatched": round(cold_p99, 2),
            "p99_ms_batched": round(warm_p99, 2),
            "coalesced_stmts": _M.BATCH_COALESCE_TOTAL.value() - c0,
            "batch_size_hist": {
                str(b): int(c) for b, c in
                zip(list(_M.BATCH_SIZE.buckets) + ["+Inf"], hist) if c},
            "hit_rate": round(hits / total_stmts, 4),
            "oracle": "ok" if mismatches == 0 else f"{mismatches} MISMATCHES",
        }
        out["configs"].append(cfg)
        log(f"# oltp {n_clients} clients: unbatched={cfg['unbatched_stmts_per_sec']}/s "
            f"batched={cfg['batched_stmts_per_sec']}/s ({cfg['speedup']}x) "
            f"p99 {cfg['p99_ms_unbatched']}->{cfg['p99_ms_batched']}ms "
            f"hit_rate={cfg['hit_rate']} oracle={cfg['oracle']}")
        if mismatches:
            log(f"# OLTP ORACLE MISMATCH at {n_clients} clients")
    # the 90/10 point-get/update mix moved to bench_mixed (ISSUE 17):
    # it is floored now (group-commit DML), so it runs two-armed on a
    # fresh catalog per arm with a serial-oracle state-hash cross-check
    return out


def _mixed_sbtest(n_rows=5000):
    """Fresh sbtest catalog for one mixed-workload arm (identical
    initial state across arms and the serial oracle)."""
    from tidb_tpu.session import Session
    from tidb_tpu.storage.catalog import Catalog

    cat = Catalog()
    boot = Session(catalog=cat)
    boot.execute("SET GLOBAL tidb_slow_log_threshold = 300000")
    boot.execute("SET GLOBAL tidb_trace_sample_rate = 0")
    boot.execute("CREATE TABLE sbtest (id bigint primary key, k bigint,"
                 " c varchar(64), pad varchar(32))")
    boot.execute("INSERT INTO sbtest VALUES " + ",".join(
        f"({i},{i % 499},'c-{i:010d}-{i * 7 % 997:04d}','pad-{i % 83}')"
        for i in range(n_rows)))
    boot.execute("ANALYZE TABLE sbtest")
    return cat, boot


def _sbtest_state_hash(cat):
    """Content hash of sbtest's committed state (order-independent of
    execution interleaving: rows sorted by primary key)."""
    import hashlib

    from tidb_tpu.session import Session

    rows = Session(catalog=cat).query(
        "select id, k, c, pad from sbtest order by id")
    return hashlib.sha256(repr(rows).encode()).hexdigest()[:16]


def bench_mixed(extra=None, n_clients=16, iters=150):
    """Mixed 90/10 point-get/point-update OLTP (ISSUE 17): the write
    path catching the read path. Two arms, each on a FRESH catalog with
    identical initial state: window=0 (every statement singleton
    through the scheduler — the pre-group-commit shape) vs the gather
    window ON (reads coalesce as before; the 10% autocommit updates now
    group-commit through the SAME window into one merged engine pass).
    Every run cross-checks the final table content hash against a
    serial one-session execution of the same statement multiset — the
    per-key updates commute (k = k + 1), so the final state is
    interleaving-invariant and the hash must match exactly."""
    import threading

    from tidb_tpu.serving import StatementScheduler
    from tidb_tpu.session import Session
    from tidb_tpu.utils import metrics as _M

    n_rows = 5000
    point_tmpl = "select c, pad, k from sbtest where id = ?"

    def key_of(client, i):
        return (client * 7919 + i * 97) % n_rows

    def run_arm(window_us):
        cat, boot = _mixed_sbtest(n_rows)
        boot.execute(f"SET GLOBAL tidb_tpu_batch_window_us = {window_us}")
        sched = StatementScheduler(cat, workers=4)
        sessions = [Session(catalog=cat) for _ in range(n_clients)]
        sids = [s.prepare(point_tmpl)[0] for s in sessions]
        sched.submit_prepared(sessions[0], sids[0], [0])
        barrier = threading.Barrier(n_clients + 1)

        def mixed(ci):
            sess, sid = sessions[ci], sids[ci]
            barrier.wait()
            for i in range(iters):
                k = key_of(ci, i)
                if i % 10 == 9:
                    sched.submit_query(
                        sess, f"update sbtest set k = k + 1 where id = {k}")
                else:
                    sched.submit_prepared(sess, sid, [k])

        threads = [threading.Thread(target=mixed, args=(ci,))
                   for ci in range(n_clients)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        adm = sched.stats_dict()
        sched.shutdown()
        return (n_clients * iters / wall, _sbtest_state_hash(cat),
                {k: adm[k] for k in ("admitted", "rejected", "timed_out")})

    hist0 = list(next(
        (c for _l, c, _s, _e in _M.DML_BATCH_SIZE.samples()), [])) or None
    c0 = _M.BATCH_COALESCE_TOTAL.value()
    cold_rps, cold_hash, _ = run_arm(0)
    warm_rps, warm_hash, adm = run_arm(1500)
    hist1 = list(next(
        (c for _l, c, _s, _e in _M.DML_BATCH_SIZE.samples()), []))
    hist = (hist1 if hist0 is None
            else [a - b for a, b in zip(hist1, hist0)])
    # serial oracle: the same statement multiset through ONE session,
    # no scheduler — the state every interleaving must reach
    cat, _boot = _mixed_sbtest(n_rows)
    oracle = Session(catalog=cat)
    for ci in range(n_clients):
        for i in range(iters):
            if i % 10 == 9:
                oracle.execute("update sbtest set k = k + 1 "
                               f"where id = {key_of(ci, i)}")
    want_hash = _sbtest_state_hash(cat)
    ok = cold_hash == want_hash and warm_hash == want_hash
    out = {
        "clients": n_clients,
        "iters": iters,
        "unbatched_stmts_per_sec": round(cold_rps, 1),
        "mixed_90_10_stmts_per_sec": round(warm_rps, 1),
        "group_commit_speedup": round(warm_rps / max(cold_rps, 1e-9), 3),
        "coalesced_stmts": _M.BATCH_COALESCE_TOTAL.value() - c0,
        "dml_batch_hist": {
            str(b): int(c) for b, c in
            zip(list(_M.DML_BATCH_SIZE.buckets) + ["+Inf"], hist) if c},
        "admission": adm,
        "oracle": "ok" if ok else (
            f"STATE HASH MISMATCH want={want_hash} "
            f"unbatched={cold_hash} batched={warm_hash}"),
    }
    log(f"# mixed 90/10 at {n_clients} clients: "
        f"unbatched={out['unbatched_stmts_per_sec']}/s "
        f"group-commit={out['mixed_90_10_stmts_per_sec']}/s "
        f"({out['group_commit_speedup']}x) oracle={out['oracle']}")
    if extra is not None:
        extra["mixed"] = out
    return out


def bench_htap(extra=None, n_clients=8, ingest_iters=160,
               analytics_iters=10, sf=0.05):
    """HTAP bench (ISSUE 17, tentpole c): analytics (TPC-H Q6 + a
    Q18-shape big-join aggregate) running DURING sustained multi-client
    ingest into the same lineitem — group-commit coalesces the insert
    stream, background compaction keeps the scan path from inheriting
    an ever-growing delta inline. Reports OLTP insert throughput,
    analytics p50/p99 under ingest, observed staleness (committed rows
    an analytics snapshot had not yet seen), and the compaction outcome
    counters. Ends with a flag-off equality check: the final Q6 with
    tidb_tpu_compaction=0 must be byte-identical to compaction ON."""
    import threading

    from tidb_tpu.serving import StatementScheduler
    from tidb_tpu.session import Session
    from tidb_tpu.storage.catalog import Catalog
    from tidb_tpu.storage.tpch import load_tpch
    from tidb_tpu.storage.tpch_queries import Q
    from tidb_tpu.utils import metrics as _M

    cat = Catalog()
    boot = Session(catalog=cat)
    boot.execute("SET GLOBAL tidb_slow_log_threshold = 300000")
    boot.execute("SET GLOBAL tidb_trace_sample_rate = 0")
    boot.execute("SET GLOBAL tidb_tpu_batch_window_us = 1500")
    # delta threshold at its floor so the ingest stream actually crosses
    # it mid-run: the fold then happens on the background worker while
    # analytics keeps scanning (the initial segmentation stays inline)
    boot.execute("SET GLOBAL tidb_tpu_segment_delta_rows = 1024")
    counts = load_tpch(cat, sf=sf, native=False)
    base_rows = counts["lineitem"]
    li = cat.table("test", "lineitem")
    ins_cols = list(li.insertable_names())
    q18_shape = (
        "select o_orderkey, sum(l_quantity) as q from lineitem "
        "join orders on l_orderkey = o_orderkey "
        "group by o_orderkey order by q desc, o_orderkey limit 10")

    sched = StatementScheduler(cat, workers=4)
    sessions = [Session(catalog=cat) for _ in range(n_clients)]
    committed = [0]          # rows committed (monotone, under lock)
    commit_lock = threading.Lock()
    barrier = threading.Barrier(n_clients + 2)
    stop = threading.Event()
    key_base = 10_000_000    # ingested l_orderkey = key_base + seq
    seq_src = iter(range(1, 1 << 30))
    seq_lock = threading.Lock()

    def ingest_row(seq):
        vals = []
        for cname in ins_cols:
            if cname == "l_orderkey":
                vals.append(str(key_base + seq))
            elif cname == "l_quantity":
                vals.append(str(1 + seq % 50))
            elif cname == "l_extendedprice":
                vals.append(str(900 + seq % 1000))
            elif cname == "l_discount":
                vals.append(f"0.0{seq % 10}")
            elif cname == "l_shipdate":
                vals.append(f"'1994-0{1 + seq % 6}-15'")
            else:
                from tidb_tpu.types import TypeKind as _TK

                c = li.schema.col(cname)
                if c.type_.is_dict_encoded:
                    vals.append("'x'")
                elif c.type_.kind in (_TK.DATE, _TK.DATETIME):
                    vals.append("'1995-01-01'")
                else:
                    vals.append("0")
        return ("insert into lineitem (" + ", ".join(ins_cols)
                + ") values (" + ", ".join(vals) + ")")

    errs = []

    def oltp(ci):
        sess = sessions[ci]
        barrier.wait()
        for _ in range(ingest_iters):
            with seq_lock:
                seq = next(seq_src)
            try:
                sched.submit_query(sess, ingest_row(seq))
                with commit_lock:
                    committed[0] += 1
            except Exception as e:  # noqa: BLE001 — reported below
                errs.append(f"{type(e).__name__}: {e}"[:200])
        stop.set()  # first finisher ends the analytics loop's tail

    lat, staleness_rows = [], []
    ana_sess = Session(catalog=cat)

    def analytics():
        barrier.wait()
        i = 0
        while True:
            with commit_lock:
                c_before = committed[0]
            sql = Q["q6"][0] if i % 2 == 0 else q18_shape
            t0 = time.perf_counter()
            sched.submit_query(ana_sess, sql)
            lat.append(time.perf_counter() - t0)
            seen = ana_sess.query(
                "select count(*) as n from lineitem "
                f"where l_orderkey >= {key_base}")[0][0]
            staleness_rows.append(max(0, c_before - seen))
            i += 1
            if i >= analytics_iters and stop.is_set():
                break

    cmp0 = {o: _M.COMPACTION_TOTAL.value(outcome=o)
            for o in ("background", "inline", "inline_fallback",
                      "discarded", "failed")}
    dml_hist0 = list(next(
        (c for _l, c, _s, _e in _M.DML_BATCH_SIZE.samples()), [])) or None
    threads = [threading.Thread(target=oltp, args=(ci,))
               for ci in range(n_clients)]
    ana = threading.Thread(target=analytics)
    for t in threads:
        t.start()
    ana.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    oltp_wall = time.perf_counter() - t0
    ana.join()
    ana_wall = time.perf_counter() - t0
    sched.shutdown()
    compaction = {o: _M.COMPACTION_TOTAL.value(outcome=o) - v
                  for o, v in cmp0.items()}
    dml_hist1 = list(next(
        (c for _l, c, _s, _e in _M.DML_BATCH_SIZE.samples()), []))
    dml_hist = (dml_hist1 if dml_hist0 is None
                else [a - b for a, b in zip(dml_hist1, dml_hist0)])
    # flag-off byte-identical: the compaction worker must never have
    # changed WHAT a scan returns, only where the rebuild ran
    chk = Session(catalog=cat)
    chk.execute("SET tidb_tpu_compaction = 0")
    off_rows = chk.query(Q["q6"][0])
    chk.execute("SET tidb_tpu_compaction = 1")
    on_rows = chk.query(Q["q6"][0])
    lats = sorted(lat)
    out = {
        "sf": sf,
        "base_rows": base_rows,
        "ingest_clients": n_clients,
        "ingested_rows": committed[0],
        "ingest_errors": errs[:3],
        "htap_oltp_stmts_per_sec": round(committed[0] / oltp_wall, 1),
        "analytics_queries": len(lat),
        "htap_analytics_qps": round(len(lat) / max(ana_wall, 1e-9), 2),
        "analytics_p50_ms": round(lats[len(lats) // 2] * 1e3, 1),
        "analytics_p99_ms": round(
            lats[max(0, int(len(lats) * 0.99) - 1)] * 1e3, 1),
        "staleness_rows_max": max(staleness_rows) if staleness_rows else 0,
        "compaction": compaction,
        "dml_batch_hist": {
            str(b): int(c) for b, c in
            zip(list(_M.DML_BATCH_SIZE.buckets) + ["+Inf"], dml_hist)
            if c},
        "flag_off_equal": repr(off_rows) == repr(on_rows),
    }
    log(f"# htap: ingest={out['htap_oltp_stmts_per_sec']}/s "
        f"analytics={out['htap_analytics_qps']}/s "
        f"p99={out['analytics_p99_ms']}ms "
        f"staleness<={out['staleness_rows_max']} rows "
        f"compaction={compaction} flag_off_equal={out['flag_off_equal']}")
    if extra is not None:
        extra["htap"] = out
    return out


def bench_pipeline(extra=None, sf=None, reps=None):
    """Fused-pipeline microbench (ISSUE 9): TPC-H Q1 + Q6 on the LOCAL
    single-chip engine — the executor spine the fused
    scan→filter→project→partial-agg path rebuilt. Two arms through the
    SAME session: the pre-PR chunk-synced tree (pipeline_fuse=0: one
    scan dispatch + one agg update + per-chunk staging every chunk) vs
    the fused pipeline (one device program per chunk, double-buffered
    prefetch, device buffer cache — a warm re-run stages nothing).
    Loud cross-checks: arms byte-identical to each other AND to the
    sqlite oracle, warm dispatch counts from the ENGINE counter
    (single-digit per fragment is the acceptance floor)."""
    from tidb_tpu.executor.pipeline import DEVICE_CACHE
    from tidb_tpu.session import Session
    from tidb_tpu.storage.catalog import Catalog
    from tidb_tpu.storage.tpch import load_tpch
    from tidb_tpu.storage.tpch_queries import Q
    from tidb_tpu.testutil import mirror_to_sqlite, rows_equal
    from tidb_tpu.utils import dispatch as _dsp

    sf = min(SF, 0.2) if sf is None else sf
    reps = REPS if reps is None else reps
    # production chunk capacity: the fragment is still genuinely
    # chunked (the 64k-row segment store feeds the unfused arm one
    # chunk per segment — the per-chunk ping-pong being measured —
    # while the fused arm packs k segments per capacity-sized batch,
    # which is where the single-digit dispatch budget comes from)
    s = Session(catalog=Catalog(), chunk_capacity=CAP)
    s.execute("SET tidb_slow_log_threshold = 300000")
    # plan reuse ON: both arms must measure EXECUTION, not re-planning
    s.execute("SET tidb_enable_non_prepared_plan_cache = 1")
    # cluster=False: this bench measures the fusion/overlap win on the
    # staging-bound Q6, so the load stays unsorted — a CLUSTER BY'd
    # lineitem lets zone maps prune ~80% of the staging in BOTH arms
    # and the ratio collapses toward compute parity (the pruning win
    # itself is bench_zone_pruning's floor, via the engine DDL path)
    counts = load_tpch(s.catalog, sf=sf, native=False, cluster=False)
    rows = counts["lineitem"]
    conn = mirror_to_sqlite(s.catalog, tables=["lineitem"])
    out = {"sf": sf, "lineitem_rows": rows, "queries": {}}

    def one(sql, fuse: bool):
        s.execute(f"SET tidb_tpu_pipeline_fuse = {int(fuse)}")
        d0 = _dsp.count()
        t0 = time.perf_counter()
        got = s.query(sql)
        return got, time.perf_counter() - t0, _dsp.count() - d0

    for name in ("q1", "q6"):
        sql, lite = Q[name]
        DEVICE_CACHE.clear()
        # warm BOTH arms (compiles, device cache fill), then interleave
        # the measured reps A/B — machine drift between back-to-back
        # blocks would otherwise bias whichever arm runs first (the
        # test_partitions lesson)
        one(sql, True)
        one(sql, False)
        fused_best = unf_best = float("inf")
        fused_disp = unf_disp = 0
        fused_rows = unf_rows = None
        # report the dispatch count of the BEST rep, not the last one:
        # a stray recompile on the final rep would otherwise misreport
        # the steady-state dispatch budget the timing reflects
        for _ in range(max(reps, 2)):
            fused_rows, dt, disp = one(sql, True)
            if dt < fused_best:
                fused_best, fused_disp = dt, disp
            unf_rows, dt, disp = one(sql, False)
            if dt < unf_best:
                unf_best, unf_disp = dt, disp
        s.execute("SET tidb_tpu_pipeline_fuse = 1")
        ok_arms, msg = rows_equal(fused_rows, unf_rows, ordered=True)
        want = conn.execute(lite or sql).fetchall()
        ok_oracle, msg2 = rows_equal(fused_rows, want, ordered=True)
        q = {
            "fused_warm_s": round(fused_best, 4),
            "unfused_warm_s": round(unf_best, 4),
            "fused_over_unfused": round(unf_best / fused_best, 3),
            "fused_warm_dispatches": fused_disp,
            "unfused_warm_dispatches": unf_disp,
            "rows_per_sec_fused": round(rows / fused_best, 1),
            "hash_equal": bool(ok_arms),
            "check": "ok" if ok_oracle else f"MISMATCH: {msg2}"[:300],
        }
        if not ok_arms:
            q["arm_mismatch"] = str(msg)[:300]
        out["queries"][name] = q
        log(f"#   {name}: fused={fused_best * 1e3:.1f}ms "
            f"({fused_disp} disp) unfused={unf_best * 1e3:.1f}ms "
            f"({unf_disp} disp) speedup={q['fused_over_unfused']}x "
            f"check={q['check']}")
    if extra is not None:
        extra["pipeline"] = out
    return out


def bench_probe(extra=None):
    """Probe-kernel microbench (ISSUE 10): searchsorted vs the
    open-addressing hash table over the (lo, hi) range contract the
    joins consume, per build/probe size, on whatever backend is live
    (the Pallas kernel rides along on TPU). CPU-runnable: the table
    path is the TPU-shaped kernel exercised with XLA window scans, so
    the regression is visible without a chip. Loud cross-check: the
    table's match counts (and lo wherever the count is non-zero) must
    equal searchsorted on every size — the chip-free half of the
    probe-mode equivalence oracle. Folded here from the orphaned
    ops/bench_probe.py so it runs (and is load-snapshotted) under the
    same protocol as every other config."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tidb_tpu.ops import hash_probe as hp
    from tidb_tpu.ops.segment_sum import pallas_enabled

    if extra is not None:
        wait_for_idle("probe_micro", extra)
        extra["probe_micro_load"] = machine_load()
    plat = __import__("jax").devices()[0].platform
    out = {"platform": plat, "max_probes": hp.MAX_PROBES,
           "counts_match": True, "sizes": []}
    rng = np.random.default_rng(7)
    for nb, npr in [(1 << 12, 1 << 20), (1 << 16, 1 << 20),
                    (1 << 18, 1 << 21)]:
        build = np.sort(rng.integers(0, 1 << 40, nb))
        probes = rng.integers(0, 1 << 41, npr)
        sh = jnp.asarray(build)
        pr = jnp.asarray(probes)
        row = {"build": nb, "probes": npr}

        def timed(fn):
            r = fn()
            jax.block_until_ready(r)
            best = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                jax.block_until_ready(fn())
                best = min(best, time.perf_counter() - t0)
            return best, r

        t_ss, r_ss = timed(lambda: jax.jit(hp.xla_probe_ranges)(sh, pr))
        row["searchsorted_s"] = round(t_ss, 5)
        t_tab, r_tab = timed(
            lambda: hp.probe_ranges(sh, pr, use_pallas=False))
        row["table_xla_s"] = round(t_tab, 5)

        def counts_ok(r):
            c_ss = np.asarray(r_ss[1]) - np.asarray(r_ss[0])
            c = np.asarray(r[1]) - np.asarray(r[0])
            nz = c_ss > 0
            return bool((c_ss == c).all()
                        and (np.asarray(r[0])[nz]
                             == np.asarray(r_ss[0])[nz]).all())

        row["counts_match"] = counts_ok(r_tab)
        if pallas_enabled():
            t_pl, r_pl = timed(
                lambda: hp.probe_ranges(sh, pr, use_pallas=True))
            row["table_pallas_s"] = round(t_pl, 5)
            row["pallas_counts_match"] = counts_ok(r_pl)
            out["counts_match"] &= row["pallas_counts_match"]
        out["counts_match"] &= row["counts_match"]
        row["table_over_searchsorted"] = round(
            t_ss / min(t_tab, row.get("table_pallas_s", t_tab)), 3)
        out["sizes"].append(row)
        log(f"# probe {nb}x{npr}: ss={t_ss * 1e3:.1f}ms "
            f"table={t_tab * 1e3:.1f}ms "
            f"({row['table_over_searchsorted']}x) "
            f"match={row['counts_match']}")
    if extra is not None:
        extra["probe_micro"] = out
    return out


def bench_join_fused(extra=None, sf=None, reps=None):
    """Fused scan→probe microbench (ISSUE 10): the Q18 fragment shape —
    lineitem (probe, plain scan) joining orders (build) under a group
    aggregate — on the LOCAL single-chip engine, fused
    (one scan+probe+expand program per chunk, build side device-cached)
    vs the chunk-synced classic tree (pipeline_fuse=0: scan dispatch +
    probe dispatch + expand dispatch per chunk, build re-drained every
    execution). Arms INTERLEAVED through the SAME session (machine
    drift must not bias one arm); plan cache on so planning noise
    cancels. Eager-agg push-down stays at its DEFAULT (on): plan
    feedback (ISSUE 15) must LEARN that the pushed plan's join cannot
    device-cache its build and select the no-push fused shape by
    measurement — the bench asserts the flip instead of pinning
    tidb_opt_agg_push_down=0 like it used to. Loud cross-checks: arms
    byte-identical to each other AND the sqlite oracle, warm fused
    dispatches from the engine counter (the <= 12 acceptance budget),
    probe-mode equivalence (searchsorted vs hash table) result-hash
    equal on the SAME fused query, and the feedback-chosen variant."""
    from tidb_tpu.executor.pipeline import DEVICE_CACHE
    from tidb_tpu.session import Session
    from tidb_tpu.storage.catalog import Catalog
    from tidb_tpu.storage.tpch import load_tpch
    from tidb_tpu.testutil import mirror_to_sqlite, rows_equal
    from tidb_tpu.utils import dispatch as _dsp

    sf = min(SF, 0.2) if sf is None else sf
    reps = REPS if reps is None else reps
    s = Session(catalog=Catalog(), chunk_capacity=CAP)
    s.execute("SET tidb_slow_log_threshold = 300000")
    s.execute("SET tidb_device_engine_mode = 'force'")
    s.execute("SET tidb_enable_non_prepared_plan_cache = 1")
    # NO tidb_opt_agg_push_down pin (ISSUE 15): with fresh stats the
    # heuristic planner pushes a partial agg below this join (the
    # eager-agg shrink gate fires on NDV evidence), which blocks the
    # fused scan→probe shape; plan feedback explores the no-push
    # alternative and keeps whichever measures faster warm — asserted
    # below. ANALYZE is the realistic production state AND what arms
    # the eager-agg decision this bench must learn through.
    # cluster=False: this bench measures the fused probe machinery on
    # the Q18 shape, where probe keys arrive in insert (orderkey) order
    # — neighboring probes then share searchsorted paths and the CPU
    # cache carries the binary rounds. A CLUSTER BY (l_shipdate)
    # lineitem randomizes probe-key order and the same join measures
    # ~6x slower on CPU (a locality artifact, not a fusion property);
    # the clustered default's end-to-end cost is guarded separately by
    # the q18_rows_per_sec flagship floor in perf_check.py.
    counts = load_tpch(s.catalog, sf=sf, native=False, cluster=False)
    s.execute("ANALYZE TABLE lineitem, orders")
    rows = counts["lineitem"]
    conn = mirror_to_sqlite(s.catalog, tables=["lineitem", "orders"])
    sql = ("select o_orderpriority, count(*) as n, sum(l_quantity) as q "
           "from lineitem join orders on l_orderkey = o_orderkey "
           "group by o_orderpriority order by o_orderpriority")

    def one(fuse: bool):
        s.execute(f"SET tidb_tpu_pipeline_fuse = {int(fuse)}")
        d0 = _dsp.count()
        t0 = time.perf_counter()
        got = s.query(sql)
        return got, time.perf_counter() - t0, _dsp.count() - d0

    DEVICE_CACHE.clear()
    from tidb_tpu.planner.feedback import STORE as FB

    FB.clear()  # a prior bench call's learning must not pre-warm this one
    # warmup doubles as feedback convergence: run 1 executes the default
    # (eager-push) plan and records it, runs 2-3 explore the no-push
    # variant cold then warm, runs 4-5 re-measure the default warm —
    # after this both variants have WARM measurements and the store
    # picks the fused no-push shape for every measured run below
    one(True)
    one(True)  # jits traced, build + scan caches parked (no-push plan)
    one(False)
    one(True)
    one(False)
    fused_best = classic_best = float("inf")
    fused_disp = classic_disp = 0
    fused_rows = classic_rows = None
    # dispatch counts track the BEST rep (the steady state the timing
    # reports), not whichever rep happened to run last
    for _ in range(max(reps, 2)):
        fused_rows, dt, disp = one(True)
        if dt < fused_best:
            fused_best, fused_disp = dt, disp
        classic_rows, dt, disp = one(False)
        if dt < classic_best:
            classic_best, classic_disp = dt, disp
    s.execute("SET tidb_tpu_pipeline_fuse = 1")
    ok_arms, msg = rows_equal(fused_rows, classic_rows, ordered=True)
    want = conn.execute(sql).fetchall()
    ok_oracle, msg2 = rows_equal(fused_rows, want, ordered=True)

    # feedback acceptance: a warm execution must select the no-push
    # (fused) plan BECAUSE the store chose it (sysvar still default-on),
    # not because of a pin — _fb_last_apd False = the override engaged
    # on the statement we just ran
    from tidb_tpu.bindinfo import normalize_sql, sql_digest

    digest = sql_digest(normalize_sql(sql))
    s.query(sql)
    last_apd = s._fb_last_apd  # before any further statement clobbers it
    chosen_by_feedback = bool(
        last_apd is False
        and FB.apd_decision(digest) is False
        and s.query("select @@tidb_opt_agg_push_down")[0][0])

    # probe-mode equivalence on the SAME fused fragment: the hash-table
    # path (the TPU-shaped kernel, runnable via XLA window scans on
    # CPU) must hash-equal the searchsorted default on every run
    s.execute("SET tidb_tpu_join_probe_mode = 'off'")
    rows_off = s.query(sql)
    s.execute("SET tidb_tpu_join_probe_mode = 'xla'")
    rows_xla = s.query(sql)
    s.execute("SET tidb_tpu_join_probe_mode = 'auto'")
    modes_equal, mode_msg = rows_equal(rows_off, rows_xla, ordered=True)

    out = {
        "sf": sf, "lineitem_rows": rows,
        "fused_warm_s": round(fused_best, 4),
        "classic_warm_s": round(classic_best, 4),
        "fused_over_classic": round(classic_best / fused_best, 3),
        "fused_warm_dispatches": fused_disp,
        "classic_warm_dispatches": classic_disp,
        "rows_per_sec_fused": round(rows / fused_best, 1),
        "hash_equal": bool(ok_arms),
        "probe_modes_equal": bool(modes_equal),
        "chosen_by_feedback": chosen_by_feedback,
        "check": "ok" if ok_oracle else f"MISMATCH: {msg2}"[:300],
    }
    if not ok_arms:
        out["arm_mismatch"] = str(msg)[:300]
    if not modes_equal:
        out["mode_mismatch"] = str(mode_msg)[:300]
    log(f"# join fused: fused={fused_best * 1e3:.1f}ms "
        f"({fused_disp} disp) classic={classic_best * 1e3:.1f}ms "
        f"({classic_disp} disp) speedup={out['fused_over_classic']}x "
        f"modes_equal={modes_equal} feedback={chosen_by_feedback} "
        f"check={out['check']}")
    conn.close()
    if extra is not None:
        extra["join_fused"] = out
    return out


def _fused_op_counts(s, sql):
    """Fused/classic attribution for one statement: run it once under
    EXPLAIN ANALYZE (which executes the REAL exec tree, open()-time
    fallback gates included) and count the FusedScan* operators in the
    rendered plan. Nodes marked ``[classic]`` delegated to the classic
    fallback at open() and count as classic, not fused. Returns
    (fused_op_count, {op_name: count})."""
    rows = s.query("explain analyze " + sql)
    ops = {}
    for row in rows:
        for tok in str(row[0]).split():
            name = tok.lstrip("└├─│ ")
            if name.startswith("FusedScan") and "[classic]" not in name:
                ops[name] = ops.get(name, 0) + 1
    return sum(ops.values()), ops


def bench_tpch_grid(extra=None, sf=None, reps=None):
    """Full TPC-H 22-query grid (ISSUE 18): every query at SF 0.1 on
    the LOCAL single-chip engine with per-query warm wall time, warm
    device-dispatch counts (engine counter), fused/classic operator
    attribution (EXPLAIN ANALYZE exec tree: FusedScanAgg/Probe/TopN
    vs the chunk-synced classics), a result hash, and an exact
    indexed-sqlite oracle check. This is the bench-side half of the
    tentpole's (d): the tier-1 grid proves 22/22 correctness at SF0.1;
    this capture records WHICH queries the fused pipeline carries and
    what each costs, so the long-tail fusion work (TopN/sort,
    multi-key/outer probes) is measured across the whole workload
    instead of hand-picked shapes.

    Attribution runs with `tidb_device_engine_mode=force`: on a
    single-CPU backend the cost-based router sends joins and generic
    aggregation to the host engine, so under `auto` the fused probes
    legitimately delegate ([classic]) and attribution would measure
    the ROUTER, not fusion coverage. Forcing the device tier answers
    the intended question — which plans run fused device operators
    when the device engine is engaged — and the forced run must stay
    row-identical to the measured auto run (`device_arm_equal`), so
    the attribution pass doubles as an extra correctness arm. Timed
    reps keep `auto`: the wall times reflect the default routing."""
    import hashlib

    from tidb_tpu.session import Session
    from tidb_tpu.storage.catalog import Catalog
    from tidb_tpu.storage.tpch import load_tpch
    from tidb_tpu.storage.tpch_queries import Q
    from tidb_tpu.testutil import (index_tpch_oracle, mirror_to_sqlite,
                                   normalize_row, rows_equal)
    from tidb_tpu.utils import dispatch as _dsp

    sf = 0.1 if sf is None else sf
    reps = REPS if reps is None else reps
    s = Session(catalog=Catalog(), chunk_capacity=CAP)
    s.execute("SET tidb_slow_log_threshold = 300000")
    s.execute("SET tidb_enable_non_prepared_plan_cache = 1")
    t0 = time.perf_counter()
    counts = load_tpch(s.catalog, sf=sf, native=False)
    conn = None
    if ORACLE:
        # indexed oracle: above toy scale the unindexed sqlite side
        # dominates grid wall time (Q4's correlated EXISTS goes
        # nested-loop); indexes keep the oracle O(probes)
        conn = index_tpch_oracle(mirror_to_sqlite(s.catalog))
    log(f"# tpch grid sf={sf} load+mirror={time.perf_counter() - t0:.1f}s")
    out = {"sf": sf, "lineitem_rows": counts["lineitem"],
           "all_exact": True, "fused_queries": 0, "queries": {}}
    vs_list = []
    for name in Q:
        sql, osql = Q[name]
        q = {}
        try:
            got = s.query(sql)  # warm: compiles, store builds, caches
            best = float("inf")
            disp = 0
            # disp tracks the BEST rep — the steady state `warm_s`
            # reports — not whichever rep happened to run last
            for _ in range(max(reps, 1)):
                d0 = _dsp.count()
                ta = time.perf_counter()
                got = s.query(sql)
                dt = time.perf_counter() - ta
                if dt < best:
                    best, disp = dt, _dsp.count() - d0
            # attribution + device arm under force (see docstring)
            s.execute("SET tidb_device_engine_mode = 'force'")
            try:
                forced = s.query(sql)
                fused_n, fused_ops = _fused_op_counts(s, sql)
            finally:
                s.execute("SET tidb_device_engine_mode = 'auto'")
            arm_ok, arm_msg = rows_equal(got, forced, ordered=True)
            h = hashlib.sha256()
            for r in got:
                h.update(repr(normalize_row(r)).encode())
            q.update({
                "warm_s": round(best, 4),
                "warm_dispatches": disp,
                "rows": len(got),
                "fused_ops": fused_n,
                "device_arm_equal": bool(arm_ok),
                "result_hash": h.hexdigest()[:16],
            })
            if not arm_ok:
                q["device_arm_mismatch"] = str(arm_msg)[:300]
                out["all_exact"] = False
            if fused_ops:
                q["fused_op_names"] = fused_ops
            if fused_n:
                out["fused_queries"] += 1
            if conn is not None:
                ta = time.perf_counter()
                want = conn.execute(osql or sql).fetchall()
                sqlite_s = time.perf_counter() - ta
                ok, msg = rows_equal(got, want, ordered=True)
                q["sqlite_s"] = round(sqlite_s, 4)
                q["vs_sqlite"] = round(sqlite_s / max(best, 1e-9), 3)
                q["check"] = "ok" if ok else f"MISMATCH: {msg}"[:300]
                if ok:
                    vs_list.append(q["vs_sqlite"])
                else:
                    out["all_exact"] = False
        except Exception as e:  # noqa: BLE001
            q["error"] = f"{type(e).__name__}: {e}"[:300]
            out["all_exact"] = False
        out["queries"][name] = q
        log(f"#   {name}: {q.get('warm_s', '-')}s "
            f"disp={q.get('warm_dispatches', '-')} "
            f"fused_ops={q.get('fused_ops', '-')} "
            f"check={q.get('check', q.get('error', 'skipped'))}")
    if vs_list:
        gm = 1.0
        for v in vs_list:
            gm *= max(v, 1e-9)
        out["vs_sqlite_geomean"] = round(gm ** (1.0 / len(vs_list)), 3)
    if conn is not None:
        conn.close()
    log(f"# tpch grid: {sum(1 for q in out['queries'].values() if q.get('check') == 'ok')}/22 exact, "
        f"{out['fused_queries']} queries with fused operators, "
        f"vs_sqlite geomean {out.get('vs_sqlite_geomean', '-')}")
    if extra is not None:
        extra["tpch_grid"] = out
    return out


def bench_topn_fused(extra=None, sf=None, reps=None):
    """Fused device top-k microbench (ISSUE 18): an ORDER BY + LIMIT
    root over a lineitem scan, fused (FusedScanTopNExec: one
    scan→top-k device program per staged chunk carrying a bounded
    winner state, ONE fetch at finalize) vs the classic tree
    (pipeline_fuse=0: chunked scan dispatches + TopNExec materializing
    EVERY child row to host before np.lexsort keeps k). Arms
    INTERLEAVED through the SAME session with the plan cache on, like
    every two-arm bench here. Loud cross-checks: arms byte-identical
    to each other AND the sqlite oracle, the fused arm actually ran a
    FusedScanTopN operator (EXPLAIN ANALYZE attribution — a silent
    fallback must not masquerade as a fused win), and the warm
    dispatch budget. The ≥1.5x floor on the "topn" row is enforced by
    perf_check.py."""
    from tidb_tpu.executor.pipeline import DEVICE_CACHE
    from tidb_tpu.planner.feedback import STORE as FB
    from tidb_tpu.session import Session
    from tidb_tpu.storage.catalog import Catalog
    from tidb_tpu.storage.tpch import load_tpch
    from tidb_tpu.testutil import mirror_to_sqlite, rows_equal
    from tidb_tpu.utils import dispatch as _dsp

    sf = min(SF, 0.2) if sf is None else sf
    reps = REPS if reps is None else reps
    s = Session(catalog=Catalog(), chunk_capacity=CAP)
    s.execute("SET tidb_slow_log_threshold = 300000")
    s.execute("SET tidb_enable_non_prepared_plan_cache = 1")
    counts = load_tpch(s.catalog, sf=sf, native=False)
    rows = counts["lineitem"]
    conn = mirror_to_sqlite(s.catalog, tables=["lineitem"]) if ORACLE else None
    out = {"sf": sf, "lineitem_rows": rows, "queries": {}}
    # single sort key = the device fast path (single-array cut). Arms
    # compare FULL rows (both resolve key ties in drain order, so they
    # must agree row-for-row); the sqlite oracle compares the sort-key
    # column only — tie MEMBERSHIP at the limit boundary is
    # implementation-defined across engines, but the key multiset of
    # the top 100 is not.
    queries = {
        "topn": (
            "select l_extendedprice, l_orderkey, l_linenumber, "
            "l_quantity from lineitem "
            "order by l_extendedprice desc limit 100",
            "select l_extendedprice from lineitem "
            "order by l_extendedprice desc limit 100"),
        "topn_filtered": (
            "select l_extendedprice, l_orderkey, l_linenumber, "
            "l_shipdate from lineitem "
            "where l_shipdate < date '1997-01-01' "
            "order by l_extendedprice desc limit 100",
            "select l_extendedprice from lineitem "
            "where l_shipdate < '1997-01-01' "
            "order by l_extendedprice desc limit 100"),
    }

    def one(sql, fuse: bool):
        s.execute(f"SET tidb_tpu_pipeline_fuse = {int(fuse)}")
        d0 = _dsp.count()
        t0 = time.perf_counter()
        got = s.query(sql)
        return got, time.perf_counter() - t0, _dsp.count() - d0

    for name, (sql, lite) in queries.items():
        DEVICE_CACHE.clear()
        FB.clear()  # learned routing must not pre-steer either arm
        one(sql, True)
        one(sql, False)
        fused_best = classic_best = float("inf")
        fused_disp = classic_disp = 0
        fused_rows = classic_rows = None
        # dispatch counts follow the best rep (see bench_tpch_grid)
        for _ in range(max(reps, 2)):
            fused_rows, dt, disp = one(sql, True)
            if dt < fused_best:
                fused_best, fused_disp = dt, disp
            classic_rows, dt, disp = one(sql, False)
            if dt < classic_best:
                classic_best, classic_disp = dt, disp
        s.execute("SET tidb_tpu_pipeline_fuse = 1")
        fused_n, fused_ops = _fused_op_counts(s, sql)
        ok_arms, msg = rows_equal(fused_rows, classic_rows, ordered=True)
        ok_oracle, msg2 = True, "ok"
        if conn is not None:
            want = conn.execute(lite).fetchall()
            ok_oracle, msg2 = rows_equal(
                [(r[0],) for r in fused_rows], want, ordered=True)
        q = {
            "fused_warm_s": round(fused_best, 4),
            "classic_warm_s": round(classic_best, 4),
            "fused_over_classic": round(classic_best / fused_best, 3),
            "fused_warm_dispatches": fused_disp,
            "classic_warm_dispatches": classic_disp,
            "rows_per_sec_fused": round(rows / fused_best, 1),
            "fused_engaged": bool(
                fused_ops.get("FusedScanTopN", 0) > 0),
            "hash_equal": bool(ok_arms),
            "check": "ok" if ok_oracle else f"MISMATCH: {msg2}"[:300],
        }
        if not ok_arms:
            q["arm_mismatch"] = str(msg)[:300]
        out["queries"][name] = q
        log(f"#   {name}: fused={fused_best * 1e3:.1f}ms "
            f"({fused_disp} disp) classic={classic_best * 1e3:.1f}ms "
            f"({classic_disp} disp) speedup={q['fused_over_classic']}x "
            f"engaged={q['fused_engaged']} check={q['check']}")
    if conn is not None:
        conn.close()
    if extra is not None:
        extra["topn_fused"] = out
    return out


def bench_zone_pruning(extra=None, sf=None, reps=None):
    """Zone-map pruning microbench (ISSUE 8): TPC-H Q6 over a
    time-ordered (l_shipdate-clustered) lineitem — the production
    fact-table layout — pruned (columnar on) vs unpruned (columnar
    off), on the LOCAL engine where the segment store lives. Loud
    cross-checks: the engine-reported pruned fraction (the acceptance
    counter), result equality across both modes, and an exact
    sqlite-oracle comparison over an integer mirror of the four Q6
    columns (scaled-int arithmetic: no float fuzz in the check).

    ISSUE 18: the clustering comes from the CLUSTER BY (l_shipdate)
    DDL default in load_tpch — ordered compaction sorts lineitem at
    the first delta→segment fold — NOT from hand-ordered ingest
    (the deprecated cluster_lineitem kwarg). The ≥2x pruning floor now
    proves the maintained layout, not load-order luck."""
    import sqlite3
    from decimal import Decimal

    import numpy as np

    from tidb_tpu.session import Session
    from tidb_tpu.storage.catalog import Catalog
    from tidb_tpu.storage.tpch import load_tpch
    from tidb_tpu.storage.tpch_queries import Q
    from tidb_tpu.types import date_to_days
    from tidb_tpu.utils import metrics as _M

    sf = min(SF, 0.2) if sf is None else sf
    reps = REPS if reps is None else reps
    s = Session(catalog=Catalog(), chunk_capacity=1 << 20)
    load_tpch(s.catalog, sf=sf, native=False)
    t = s.catalog.table("test", "lineitem")
    n = t.n
    sql = Q["q6"][0]

    def segs():
        return (int(_M.SCAN_SEGMENTS_SCANNED_TOTAL.value()),
                int(_M.SCAN_SEGMENTS_PRUNED_TOTAL.value()))

    # warm both modes (store build + XLA compiles happen here)
    got_on = s.query(sql)
    s0 = segs()
    got_on = s.query(sql)
    s1 = segs()
    scanned, pruned = s1[0] - s0[0], s1[1] - s0[1]
    frac = pruned / max(scanned + pruned, 1)
    best_on = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        got_on = s.query(sql)
        best_on = min(best_on, time.perf_counter() - t0)
    s.execute("set tidb_tpu_columnar_enable = 0")
    got_off = s.query(sql)  # warm the raw path
    best_off = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        got_off = s.query(sql)
        best_off = min(best_off, time.perf_counter() - t0)
    s.execute("set tidb_tpu_columnar_enable = 1")

    # exact oracle: integer mirror of the four Q6 columns; revenue at
    # scale 4 (price scale 2 x discount scale 2) compares as an int
    conn = sqlite3.connect(":memory:")
    conn.execute("create table li (ship integer, disc integer, "
                 "qty integer, ext integer)")
    rows = np.stack([
        np.asarray(t.data["l_shipdate"][:n], dtype=np.int64),
        np.asarray(t.data["l_discount"][:n], dtype=np.int64),
        np.asarray(t.data["l_quantity"][:n], dtype=np.int64),
        np.asarray(t.data["l_extendedprice"][:n], dtype=np.int64),
    ], axis=1)
    conn.executemany("insert into li values (?,?,?,?)",
                     map(tuple, rows.tolist()))
    d1 = date_to_days(__import__("datetime").date(1994, 1, 1))
    d2 = date_to_days(__import__("datetime").date(1995, 1, 1))
    want = conn.execute(
        f"select sum(ext * disc) from li where ship >= {d1} and "
        f"ship < {d2} and disc between 5 and 7 and qty < 2400"
    ).fetchone()[0] or 0
    conn.close()
    got_scaled = int(Decimal(str(got_on[0][0] or 0)).scaleb(4))
    check = "ok"
    if got_scaled != int(want):
        check = f"MISMATCH: engine {got_scaled} != sqlite {int(want)}"
    if got_on != got_off:
        # append, don't overwrite: both diagnostics matter when both fail
        extra_msg = f"MISMATCH: pruned {got_on} != unpruned {got_off}"
        check = extra_msg if check == "ok" else f"{check}; {extra_msg}"
    out = {
        "sf": sf,
        "rows": int(n),
        "pruned_s": round(best_on, 4),
        "unpruned_s": round(best_off, 4),
        "pruned_over_unpruned": round(best_off / max(best_on, 1e-9), 3),
        "segs_scanned": scanned,
        "segs_pruned": pruned,
        "pruned_fraction": round(frac, 4),
        "check": check,
        # ISSUE 19 satellite: stamp the capture so perf_check (and a
        # reader of BENCH_r*) can tell machine drift from regression —
        # the SF1 ratio sits near its floor, provenance names the rev
        # and flag set that produced each number
        "provenance": bench_provenance(),
    }
    log(f"# zone pruning q6 sf={sf}: pruned={best_on * 1e3:.1f}ms "
        f"unpruned={best_off * 1e3:.1f}ms "
        f"({out['pruned_over_unpruned']}x), "
        f"segs {scanned}/{scanned + pruned} scanned "
        f"(frac pruned {frac:.2f}) check={check}")
    if extra is not None:
        extra["zone_pruning"] = out
    return out


def bench_budget_q18(catalog, extra=None):
    """Budget-capped q18 via segment spill (ISSUE 8): the same query,
    resident vs under a statement memory budget of half the segment
    store's resident bytes, on a LOCAL (no-mesh) session over an
    already-loaded TPC-H catalog. The budget run must complete by
    evicting/re-materializing segments (engine spill counters move)
    and produce byte-identical rows."""
    import hashlib

    from tidb_tpu.session import Session
    from tidb_tpu.storage.tpch_queries import Q
    from tidb_tpu.utils import metrics as _M

    s = Session(catalog=catalog, chunk_capacity=1 << 20)
    sql = Q["q18"][0]

    def result_hash(rows):
        h = hashlib.sha256()
        for r in rows:
            h.update(repr(r).encode())
        return h.hexdigest()[:16]

    s.query(sql)  # warm: builds stores, compiles
    t0 = time.perf_counter()
    resident = s.query(sql)
    resident_s = time.perf_counter() - t0
    li = s.catalog.table("test", "lineitem")
    store = getattr(li, "_segment_store", None)
    seg_bytes = store.resident_bytes() if store is not None else 0
    budget = max(64 << 20, seg_bytes // 2)
    out0 = _M.SPILL_SEGMENT_BYTES.value(dir="out")
    in0 = _M.SPILL_SEGMENT_BYTES.value(dir="in")
    s.execute(f"set tidb_mem_quota_query = {budget}")
    s.execute("set tidb_enable_tmp_storage_on_oom = 1")
    t0 = time.perf_counter()
    budgeted = s.query(sql)
    budget_s = time.perf_counter() - t0
    s.execute("set tidb_mem_quota_query = 2147483648")
    spill_out = int(_M.SPILL_SEGMENT_BYTES.value(dir="out") - out0)
    spill_in = int(_M.SPILL_SEGMENT_BYTES.value(dir="in") - in0)
    out = {
        "budget_bytes": int(budget),
        "segment_resident_bytes": int(seg_bytes),
        "resident_s": round(resident_s, 4),
        "budget_s": round(budget_s, 4),
        "overhead_vs_resident": round(budget_s / max(resident_s, 1e-9), 3),
        "spill_out_bytes": int(spill_out),
        "spill_in_bytes": int(spill_in),
        "hash_equal": result_hash(budgeted) == result_hash(resident),
        "result_hash": result_hash(resident),
    }
    log(f"# q18 budget: resident={resident_s:.2f}s "
        f"budget({budget >> 20}MiB)={budget_s:.2f}s "
        f"spill out={spill_out >> 20}MiB in={spill_in >> 20}MiB "
        f"hash_equal={out['hash_equal']}")
    if extra is not None:
        extra["q18_budget"] = out
    return out


def main(locked_detail=("acquired", "acquired")):
    extra = {}
    extra["chip_lock"] = locked_detail[1]
    if locked_detail[0] == "unavailable":
        # never start a TPU client while another live process holds the
        # chip — run the whole bench pinned to CPU instead
        os.environ["BENCH_PLATFORM"] = "cpu"
    platform, detail = pick_platform()
    extra["platform"] = platform
    if platform != "default":
        # pin before importing jax anywhere in this process
        os.environ["JAX_PLATFORMS"] = platform
        extra["platform_detail"] = detail[-300:]
        log(f"# falling back to platform={platform}: {detail[-200:]}")
    else:
        log(f"# backend probe: {detail}")

    import tidb_tpu  # noqa: F401  (jax x64 config)
    import jax

    if platform != "default":
        jax.config.update("jax_platforms", platform)
    else:
        # tunneled-TPU path: every remote_compile pays seconds of tunnel
        # latency regardless of program size, and serialized executables
        # DO round-trip through the persistent cache here — cache nearly
        # everything. (The 10s default stays for CPU runs: XLA:CPU AOT
        # artifacts embed host-feature flags and must not be shared
        # across processes with/without the TPU plugin loaded.)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    from tidb_tpu.parallel import make_mesh
    from tidb_tpu.session import Session
    from tidb_tpu.storage.tpch import load_tpch
    from tidb_tpu.storage.tpch_queries import Q

    extra["devices"] = [str(d) for d in jax.devices()][:8]

    t0 = time.perf_counter()
    # mesh session even on one chip: tables stay device-resident in the
    # shard cache and each query is one collective fragment dispatch
    mesh = make_mesh()
    s = Session(chunk_capacity=CAP, mesh=mesh)
    counts = load_tpch(s.catalog, sf=SF)
    rows = counts["lineitem"]
    extra["sf"] = SF
    extra["lineitem_rows"] = rows
    log(f"# sf={SF} lineitem={rows} gen={time.perf_counter() - t0:.1f}s")

    conn = None
    if ORACLE:
        from tidb_tpu.testutil import mirror_to_sqlite

        t0 = time.perf_counter()
        conn = mirror_to_sqlite(s.catalog, tables=["lineitem", "orders", "customer"])
        log(f"# sqlite mirror {time.perf_counter() - t0:.1f}s")

    # headline: Q1 (scan + filter + group-by agg) ---------------------------
    log("# q1")
    q1_rps, q1_vs, q1_best, q1_check = bench_query(
        s, Q["q1"][0], conn, Q["q1"][1] or Q["q1"][0], rows, extra=extra, tag="q1")
    if "MISMATCH" in q1_check:
        extra["q1_check"] = q1_check

    # Q6: range-predicate selection -> device filter kernel ------------------
    try:
        log("# q6")
        sql, lite = Q["q6"]
        rps, vs, best, check = bench_query(s, sql, conn, lite or sql, rows,
                                           extra=extra, tag="q6")
        extra["tpch_q6_rows_per_sec"] = round(rps, 1)
        extra["q6_vs_sqlite"] = round(vs, 3)
        # bytes actually consulted by Q6: 4 numeric lineitem columns
        extra["tpch_q6_gbps"] = round(rows * 4 * 8 / best / 1e9, 3)
        if "MISMATCH" in check:
            extra["q6_check"] = check
    except Exception as e:  # noqa: BLE001
        extra["q6_error"] = f"{type(e).__name__}: {e}"[:300]

    # join microbench: lineitem x orders build+probe throughput --------------
    try:
        log("# join microbench")
        jq = ("select count(*) as n, sum(l_quantity) as q from lineitem "
              "join orders on l_orderkey = o_orderkey where o_totalprice > 100000")
        rps, vs, best, check = bench_query(s, jq, conn, jq, rows,
                                           extra=extra, tag="join")
        # bytes through the join: probe keys+payload and build keys+filter col
        jbytes = rows * 2 * 8 + counts["orders"] * 2 * 8
        extra["join_build_probe_gbps"] = round(jbytes / best / 1e9, 3)
        extra["join_vs_sqlite"] = round(vs, 3)
        if "MISMATCH" in check:
            extra["join_check"] = check
    except Exception as e:  # noqa: BLE001
        extra["join_error"] = f"{type(e).__name__}: {e}"[:300]

    # plan-cache microbench: the OLTP statement path (host-only; no mesh
    # or sqlite involvement — the win being measured is Python planning)
    try:
        log("# plan cache microbench")
        extra["plan_cache"] = bench_plan_cache(extra)
    except Exception as e:  # noqa: BLE001
        extra["plan_cache_error"] = f"{type(e).__name__}: {e}"[:300]

    # release the SF1 working set before the join-heavy configs: keeping
    # gigabytes of prior sessions resident measurably slows the numpy/
    # XLA paths of later configs (page-cache pressure)
    import gc

    def drop(*objs):
        for o in objs:
            try:
                if hasattr(o, "close"):
                    o.close()
            except Exception:  # noqa: BLE001
                pass
        gc.collect()

    # Q18: 3-way join + large-key agg (BASELINE flagship config) -------------
    try:
        log(f"# q18 at sf={SF_Q18}")
        if abs(SF_Q18 - SF) > 1e-9:
            # separate data set: the SF1 working set is no longer needed
            drop(conn)
            s = counts = conn = None
            gc.collect()
            s18 = Session(chunk_capacity=CAP, mesh=mesh)
            c18 = load_tpch(s18.catalog, sf=SF_Q18)
            conn18 = None
            if ORACLE:
                from tidb_tpu.testutil import mirror_to_sqlite

                conn18 = mirror_to_sqlite(
                    s18.catalog, tables=["lineitem", "orders", "customer"])
        else:
            s18, c18, conn18 = s, counts, conn
        sql, lite = Q["q18"]
        rps, vs, best, check = bench_query(
            s18, sql, conn18, lite or sql, c18["lineitem"], extra=extra, tag="q18")
        extra["tpch_q18_rows_per_sec"] = round(rps, 1)
        extra["q18_vs_sqlite"] = round(vs, 3)
        extra["q18_sf"] = SF_Q18
        if "MISMATCH" in check:
            extra["q18_check"] = check
    except Exception as e:  # noqa: BLE001
        extra["q18_error"] = f"{type(e).__name__}: {e}"[:300]

    # Q18 streamed: the same query under a MEMORY BUDGET of lineitem/4
    # (VERDICT r4 task 4 / SURVEY.md:315 hard-part 6 at bench scale).
    # The budget binds whichever engine the router picks: the device
    # tier streams lineitem through fixed [P, R] fragment batches
    # (tidb_device_cache_bytes), the host tier spills runs and finishes
    # with the key-range external aggregation merge
    # (tidb_mem_quota_query). Either path counts as engaged; forcing a
    # mismatched engine would measure the budget against the wrong tier.
    try:
        if "q18_error" not in extra and s18 is not None:
            from tidb_tpu.parallel.partition import table_bytes
            from tidb_tpu.utils.metrics import EXTERNAL_AGG, FRAGMENT_DISPATCH

            def stream_engagements():
                return (FRAGMENT_DISPATCH.value(kind="general_segment_stream")
                        + FRAGMENT_DISPATCH.value(kind="general_generic_stream")
                        + EXTERNAL_AGG.value())

            li = s18.catalog.table("test", "lineitem")
            li_bytes = table_bytes(li)
            budget = max(1 << 20, li_bytes // 4)
            log(f"# q18 streamed (lineitem={li_bytes >> 20}MiB, "
                f"budget={budget >> 20}MiB)")
            best_res = best
            s18.execute(f"SET tidb_device_cache_bytes = {budget}")
            # the HOST quota floors at the engine's fixed per-query
            # working set (chunk buffers + scan staging ~ tens of MB):
            # at toy smoke SFs lineitem/4 dips below it and would OOM
            # on overhead, not on group state
            s18.execute(f"SET tidb_mem_quota_query = {max(budget, 32 << 20)}")
            s18.execute("SET tidb_enable_tmp_storage_on_oom = 1")
            d0 = stream_engagements()
            rps_s, vs_s, best_s, check_s = bench_query(
                s18, sql, conn18, lite or sql, c18["lineitem"],
                extra=extra, tag="q18_streamed")
            engaged = stream_engagements() > d0
            s18.execute("SET tidb_device_cache_bytes = 8589934592")
            s18.execute("SET tidb_mem_quota_query = 2147483648")  # default
            extra["q18_streamed"] = {
                "rows_per_sec": round(rps_s, 1),
                "vs_sqlite": round(vs_s, 3),
                "budget_bytes": budget,
                "lineitem_bytes": li_bytes,
                "engaged": bool(engaged),
                "overhead_vs_resident": round(best_s / best_res, 3),
                "check": check_s,
            }
    except Exception as e:  # noqa: BLE001
        extra["q18_streamed_error"] = f"{type(e).__name__}: {e}"[:300]

    # Q18 under a segment-spill budget (ISSUE 8): local engine over the
    # same catalog — completes by evicting/re-materializing segments,
    # byte-identical to the resident run
    try:
        if "q18_error" not in extra and s18 is not None:
            log("# q18 budget (segment spill)")
            bench_budget_q18(s18.catalog, extra)
    except Exception as e:  # noqa: BLE001
        extra["q18_budget_error"] = f"{type(e).__name__}: {e}"[:300]

    # SSB Q3.2: 4-way star join (BASELINE flagship config) -------------------
    try:
        log(f"# ssb q3.2 at sf={SF_SSB}")
        drop(locals().get("conn18"))
        s18 = conn18 = c18 = None
        gc.collect()
        from tidb_tpu.storage.ssb import SSB_QUERIES, load_ssb

        s_ssb = Session(chunk_capacity=CAP, mesh=mesh)
        c_ssb = load_ssb(s_ssb.catalog, sf=SF_SSB)
        conn_ssb = None
        if ORACLE:
            from tidb_tpu.testutil import mirror_to_sqlite

            conn_ssb = mirror_to_sqlite(s_ssb.catalog)
        sql = SSB_QUERIES["q3.2"]
        # unordered: q3.2's ORDER BY doesn't break revenue ties
        rps, vs, best, check = bench_query(
            s_ssb, sql, conn_ssb, sql, c_ssb["lineorder"], ordered=False,
            extra=extra, tag="ssb")
        extra["ssb_q32_rows_per_sec"] = round(rps, 1)
        extra["ssb_q32_vs_sqlite"] = round(vs, 3)
        extra["ssb_sf"] = SF_SSB
        if "MISMATCH" in check:
            extra["ssb_q32_check"] = check
    except Exception as e:  # noqa: BLE001
        extra["ssb_error"] = f"{type(e).__name__}: {e}"[:300]

    # TPC-DS Q95: semi-join / MPP exchange config ----------------------------
    try:
        log(f"# tpcds q95 at sf={SF_DS}")
        drop(locals().get("conn_ssb"))
        s_ssb = conn_ssb = c_ssb = None
        gc.collect()
        from tidb_tpu.storage.tpcds import Q95, Q95_SQLITE, load_tpcds_q95

        s_ds = Session(chunk_capacity=CAP, mesh=mesh)
        c_ds = load_tpcds_q95(s_ds.catalog, sf=SF_DS)
        conn_ds = None
        if ORACLE:
            from tidb_tpu.testutil import mirror_to_sqlite

            conn_ds = mirror_to_sqlite(s_ds.catalog)
        rps, vs, best, check = bench_query(
            s_ds, Q95, conn_ds, Q95_SQLITE, c_ds["web_sales"], extra=extra, tag="tpcds")
        extra["tpcds_q95_rows_per_sec"] = round(rps, 1)
        extra["tpcds_q95_vs_sqlite"] = round(vs, 3)
        extra["tpcds_sf"] = SF_DS
        if "MISMATCH" in check:
            extra["tpcds_q95_check"] = check
    except Exception as e:  # noqa: BLE001
        extra["tpcds_error"] = f"{type(e).__name__}: {e}"[:300]

    # fused-pipeline microbench (ISSUE 9): Q1/Q6 fused vs chunk-synced
    # on the single-chip spine, warm dispatch counts + oracle
    try:
        log("# pipeline microbench")
        bench_pipeline(extra)
    except Exception as e:  # noqa: BLE001
        extra["pipeline_error"] = f"{type(e).__name__}: {e}"[:300]

    # fused scan→probe microbench (ISSUE 10): the Q18 join fragment
    # fused vs classic + probe-mode equivalence, dispatch budget
    try:
        log("# join fused microbench")
        bench_join_fused(extra)
    except Exception as e:  # noqa: BLE001
        extra["join_fused_error"] = f"{type(e).__name__}: {e}"[:300]

    # fused TopN microbench (ISSUE 18): ORDER BY + LIMIT root fused
    # (device top-k state, one finalize fetch) vs classic materializing
    # sort, interleaved arms + oracle
    try:
        log("# topn fused microbench")
        bench_topn_fused(extra)
    except Exception as e:  # noqa: BLE001
        extra["topn_fused_error"] = f"{type(e).__name__}: {e}"[:300]

    # full TPC-H 22-query grid (ISSUE 18): per-query warm time,
    # dispatch counts, fused/classic attribution, indexed-sqlite oracle
    try:
        log("# tpch 22-query grid")
        bench_tpch_grid(extra)
    except Exception as e:  # noqa: BLE001
        extra["tpch_grid_error"] = f"{type(e).__name__}: {e}"[:300]

    # probe-kernel microbench (ISSUE 10): searchsorted vs hash table,
    # per backend — the TPU-vs-CPU join-kernel regression guard
    try:
        log("# probe kernel microbench")
        bench_probe(extra)
    except Exception as e:  # noqa: BLE001
        extra["probe_micro_error"] = f"{type(e).__name__}: {e}"[:300]

    # zone-map pruning microbench (ISSUE 8): Q6 over time-ordered
    # lineitem, pruned vs unpruned, engine counters + exact oracle
    try:
        drop(locals().get("conn_ds"))
        s_ds = conn_ds = c_ds = None
        gc.collect()
        log("# zone-map pruning microbench")
        bench_zone_pruning(extra)
    except Exception as e:  # noqa: BLE001
        extra["zone_pruning_error"] = f"{type(e).__name__}: {e}"[:300]

    # join microbench: the local-engine partitioned join (ISSUE 3) —
    # build x probe grid, cold vs warm, sqlite oracle + retrace guards.
    # LAST, after the big working sets are released: the >=5x acceptance
    # number must not absorb another config's page-cache pressure (the
    # baseline was measured on an idle machine)
    try:
        drop(locals().get("conn_ds"))
        s_ds = conn_ds = c_ds = None
        gc.collect()
        log("# join microbench")
        extra["join_micro"] = bench_join_micro(extra)
    except Exception as e:  # noqa: BLE001
        extra["join_micro_error"] = f"{type(e).__name__}: {e}"[:300]

    # multi-client OLTP through the serving tier (ISSUE 7): coalesced vs
    # unbatched stmts/s + p99 + admission counters, serial-oracle checked
    # (host-only: the win being measured is scheduling + batched dispatch)
    try:
        log("# oltp serving bench")
        extra["oltp"] = bench_oltp(extra)
    except Exception as e:  # noqa: BLE001
        extra["oltp_error"] = f"{type(e).__name__}: {e}"[:300]

    # mixed 90/10 with group-commit DML (ISSUE 17): window on vs off on
    # fresh catalogs, serial-oracle state-hash checked every run
    try:
        log("# mixed 90/10 group-commit bench")
        bench_mixed(extra)
    except Exception as e:  # noqa: BLE001
        extra["mixed_error"] = f"{type(e).__name__}: {e}"[:300]

    # HTAP: analytics during sustained ingest with background
    # compaction ON (ISSUE 17), staleness + p99 + flag-off equality
    try:
        log("# htap bench")
        bench_htap(extra)
    except Exception as e:  # noqa: BLE001
        extra["htap_error"] = f"{type(e).__name__}: {e}"[:300]

    # sharded scale-out capture (ISSUE 13): same scan-agg at 1/2/4
    # workers over SHARD BY placement -> MULTICHIP_r06.json
    try:
        log("# multichip scale-out bench")
        bench_multichip(extra)
    except Exception as e:  # noqa: BLE001
        extra["multichip_error"] = f"{type(e).__name__}: {e}"[:300]

    # elastic-topology SLO (ISSUE 19): p99 + throughput dip during a
    # live 12->24 online reshard under sustained mixed traffic; the
    # serving floor (every 1s window serves) is gated in perf_check
    try:
        log("# elastic reshard bench")
        bench_elastic(extra)
    except Exception as e:  # noqa: BLE001
        extra["elastic_error"] = f"{type(e).__name__}: {e}"[:300]

    extra["provenance"] = bench_provenance()
    print(json.dumps({
        "metric": "tpch_q1_rows_per_sec",
        "value": round(q1_rps, 1),
        "unit": "rows/sec",
        "vs_baseline": round(q1_vs, 3),
        "extra": extra,
    }))


if __name__ == "__main__":
    _lock = chip_lock()
    try:
        main(_lock)
    except Exception as e:  # noqa: BLE001
        # a failed bench must still produce a diagnosable one-line artifact
        traceback.print_exc()
        print(json.dumps({
            "metric": "tpch_q1_rows_per_sec",
            "value": 0.0,
            "unit": "rows/sec",
            "vs_baseline": 0.0,
            "extra": {"error": f"{type(e).__name__}: {e}"[:500]},
        }))
        sys.exit(0)
    finally:
        chip_unlock(_lock[0])
