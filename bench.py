#!/usr/bin/env python
"""Benchmark driver: TPC-H Q1 end-to-end throughput on the current JAX
backend (the BASELINE.json "TPC-H rows/sec/chip" metric, Q1 config).

Prints ONE json line:
  {"metric": "tpch_q1_rows_per_sec", "value": N, "unit": "rows/sec",
   "vs_baseline": R}

vs_baseline is measured against an in-process CPU SQL executor (stdlib
sqlite3) running the identical query over the identical data — the
stand-in for the reference's CPU vectorized executor, which is
unavailable in this environment (BASELINE.json ships "published": {};
see BASELINE.md). The north-star target is >=5x the CPU executor.

Env knobs: BENCH_SF (default 1.0), BENCH_REPS (default 3),
BENCH_CHUNK (default 2^20 rows), BENCH_ORACLE=0 to skip the sqlite
baseline (vs_baseline reported as 0.0).
"""

import json
import os
import sys
import time

SF = float(os.environ.get("BENCH_SF", "1.0"))
REPS = int(os.environ.get("BENCH_REPS", "3"))
CAP = int(os.environ.get("BENCH_CHUNK", str(1 << 20)))
ORACLE = os.environ.get("BENCH_ORACLE", "1") != "0"

Q1 = """select l_returnflag, l_linestatus,
               sum(l_quantity) as sum_qty,
               sum(l_extendedprice) as sum_base_price,
               sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
               sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
               avg(l_quantity) as avg_qty,
               avg(l_extendedprice) as avg_price,
               avg(l_discount) as avg_disc,
               count(*) as count_order
        from lineitem
        where l_shipdate <= date '1998-12-01' - interval '90' day
        group by l_returnflag, l_linestatus
        order by l_returnflag, l_linestatus"""

Q1_SQLITE = Q1.replace("date '1998-12-01' - interval '90' day", "'1998-09-02'")


def main():
    import tidb_tpu  # noqa: F401  (jax x64 config)
    from tidb_tpu.parallel import make_mesh
    from tidb_tpu.session import Session
    from tidb_tpu.storage.tpch import load_tpch

    t0 = time.perf_counter()
    # mesh session even on one chip: tables stay device-resident in the
    # shard cache and each query is one collective fragment dispatch
    mesh = make_mesh()
    s = Session(chunk_capacity=CAP, mesh=mesh)
    counts = load_tpch(s.catalog, sf=SF)
    rows = counts["lineitem"]
    gen_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    warm = s.query(Q1)  # compile + warmup
    warm_s = time.perf_counter() - t0
    assert len(warm) >= 1

    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        got = s.query(Q1)
        best = min(best, time.perf_counter() - t0)
    rps = rows / best

    vs = 0.0
    cpu_s = None
    if ORACLE:
        from tidb_tpu.testutil import mirror_to_sqlite, rows_equal

        t0 = time.perf_counter()
        conn = mirror_to_sqlite(s.catalog, tables=["lineitem"])
        mirror_s = time.perf_counter() - t0
        cpu_s = float("inf")
        for _ in range(max(1, REPS - 1)):
            t0 = time.perf_counter()
            want = conn.execute(Q1_SQLITE).fetchall()
            cpu_s = min(cpu_s, time.perf_counter() - t0)
        ok, msg = rows_equal(got, want, ordered=True)
        if not ok:
            print(f"RESULT MISMATCH vs sqlite oracle: {msg}", file=sys.stderr)
            sys.exit(1)
        vs = cpu_s / best
        print(
            f"# sf={SF} rows={rows} gen={gen_s:.1f}s warmup={warm_s:.2f}s "
            f"best={best * 1e3:.1f}ms sqlite_mirror={mirror_s:.1f}s "
            f"sqlite_best={cpu_s * 1e3:.1f}ms",
            file=sys.stderr,
        )

    print(json.dumps({
        "metric": "tpch_q1_rows_per_sec",
        "value": round(rps, 1),
        "unit": "rows/sec",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
