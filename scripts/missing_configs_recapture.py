#!/usr/bin/env python
"""On-chip recapture of the configs the tunnel has denied so far:
Q18 (+streamed), SSB Q3.2, TPC-DS Q95.

Both round-4 captures lost these to mid-run tunnel deaths (remote
compiles through the HTTP tunnel take minutes per program and the
backend drops). This retakes ONLY the still-missing configs under the
chip lock — configs that already landed in BENCH_tpu.json are skipped,
each success patches in immediately, and a mid-run tunnel death
records its error and leaves earlier results intact.

Run solo (acquires the chip lock via bench.chip_lock).
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def patch(updates):
    path = os.path.join(REPO, "BENCH_tpu.json")
    art = json.load(open(path))
    # strip STALE errors for tags this run recaptured — but never an
    # error this very run just recorded (a half-failed config must stay
    # visibly failed so the watchdog retries it)
    stale = [k for k in art["extra"]
             if k.endswith("_error") and k[:-6] + "_recaptured" in updates
             and k not in updates]
    art["extra"].update(updates)
    for k in stale:
        art["extra"].pop(k, None)
    tmp = path + ".patch"
    json.dump(art, open(tmp, "w"))
    os.replace(tmp, path)


def capture_q18(mesh, out):
    from tidb_tpu.session import Session
    from tidb_tpu.storage.tpch import load_tpch
    from tidb_tpu.storage.tpch_queries import Q
    from tidb_tpu.testutil import mirror_to_sqlite

    sf = float(os.environ.get("BENCH_SF_Q18", "0.2"))
    s = Session(chunk_capacity=1 << 20, mesh=mesh)
    counts = load_tpch(s.catalog, sf=sf)
    conn = mirror_to_sqlite(s.catalog,
                            tables=["lineitem", "orders", "customer"])
    sql, lite = Q["q18"]
    rps, vs, best, check = bench.bench_query(
        s, sql, conn, lite or sql, counts["lineitem"], reps=2,
        extra=out, tag="q18")
    out["tpch_q18_rows_per_sec"] = round(rps, 1)
    out["q18_vs_sqlite"] = round(vs, 3)
    out["q18_sf"] = sf
    out["q18_recaptured"] = True
    if "MISMATCH" in check:
        out["q18_check"] = check
    print(f"q18: {rps:.1f} rows/s {vs:.3f}x {check}", flush=True)

    from tidb_tpu.parallel.partition import table_bytes
    from tidb_tpu.utils.metrics import FRAGMENT_DISPATCH

    def sd():
        return (FRAGMENT_DISPATCH.value(kind="general_segment_stream")
                + FRAGMENT_DISPATCH.value(kind="general_generic_stream"))

    li = s.catalog.table("test", "lineitem")
    budget = max(1 << 20, table_bytes(li) // 4)
    try:
        best_res = best
        s.execute(f"SET tidb_device_cache_bytes = {budget}")
        d0 = sd()
        rps_s, vs_s, best_s, check_s = bench.bench_query(
            s, sql, conn, lite or sql, counts["lineitem"], reps=2,
            extra=out, tag="q18_streamed")
        engaged = sd() > d0
        if not engaged:
            # mirror bench.py: auto routing bypassed the fragment tier,
            # so force the device engine for a true streamed/resident
            # pair instead of recording a meaningless ratio
            print("q18 streamed: forcing device engine for a true pair",
                  flush=True)
            s.execute("SET tidb_device_engine_mode = 'force'")
            s.execute("SET tidb_device_cache_bytes = 8589934592")
            _, _, best_res, _ = bench.bench_query(
                s, sql, conn, lite or sql, counts["lineitem"], reps=2)
            s.execute(f"SET tidb_device_cache_bytes = {budget}")
            d0 = sd()
            rps_s, vs_s, best_s, check_s = bench.bench_query(
                s, sql, conn, lite or sql, counts["lineitem"], reps=2,
                extra=out, tag="q18_streamed")
            engaged = sd() > d0
            s.execute("SET tidb_device_engine_mode = 'auto'")
        out["q18_streamed"] = {
            "rows_per_sec": round(rps_s, 1), "vs_sqlite": round(vs_s, 3),
            "budget_bytes": budget, "lineitem_bytes": table_bytes(li),
            "engaged": bool(engaged),
            "overhead_vs_resident": round(best_s / best_res, 3),
            "check": check_s,
        }
        # marks a stale q18_streamed_error from an earlier half-failed
        # run for removal by patch()
        out["q18_streamed_recaptured"] = True
    except Exception as e:  # noqa: BLE001 — q18 itself still landed
        out["q18_streamed_error"] = f"{type(e).__name__}: {e}"[:300]
    finally:
        s.execute("SET tidb_device_cache_bytes = 8589934592")
        conn.close()


def capture_ssb(mesh, out):
    from tidb_tpu.session import Session
    from tidb_tpu.storage.ssb import SSB_QUERIES, load_ssb
    from tidb_tpu.testutil import mirror_to_sqlite

    sf = float(os.environ.get("BENCH_SF_SSB", "0.1"))
    s = Session(chunk_capacity=1 << 20, mesh=mesh)
    c = load_ssb(s.catalog, sf=sf)
    conn = mirror_to_sqlite(s.catalog)
    sql = SSB_QUERIES["q3.2"]
    rps, vs, _best, check = bench.bench_query(
        s, sql, conn, sql, c["lineorder"], reps=2, ordered=False,
        extra=out, tag="ssb")
    out["ssb_q32_rows_per_sec"] = round(rps, 1)
    out["ssb_q32_vs_sqlite"] = round(vs, 3)
    out["ssb_sf"] = sf
    out["ssb_recaptured"] = True
    if "MISMATCH" in check:
        out["ssb_q32_check"] = check
    print(f"ssb: {rps:.1f} rows/s {vs:.3f}x {check}", flush=True)
    conn.close()


def capture_tpcds(mesh, out):
    from tidb_tpu.session import Session
    from tidb_tpu.storage.tpcds import Q95, Q95_SQLITE, load_tpcds_q95
    from tidb_tpu.testutil import mirror_to_sqlite

    sf = float(os.environ.get("BENCH_SF_DS", "0.5"))
    s = Session(chunk_capacity=1 << 20, mesh=mesh)
    c = load_tpcds_q95(s.catalog, sf=sf)
    conn = mirror_to_sqlite(s.catalog)
    rps, vs, _best, check = bench.bench_query(
        s, Q95, conn, Q95_SQLITE, c["web_sales"], reps=2,
        extra=out, tag="tpcds")
    out["tpcds_q95_rows_per_sec"] = round(rps, 1)
    out["tpcds_q95_vs_sqlite"] = round(vs, 3)
    out["tpcds_sf"] = sf
    out["tpcds_recaptured"] = True
    if "MISMATCH" in check:
        out["tpcds_q95_check"] = check
    print(f"tpcds: {rps:.1f} rows/s {vs:.3f}x {check}", flush=True)
    conn.close()


CONFIGS = [
    ("tpch_q18_rows_per_sec", "q18", capture_q18),
    ("ssb_q32_rows_per_sec", "ssb", capture_ssb),
    ("tpcds_q95_rows_per_sec", "tpcds", capture_tpcds),
]


def missing_count(extra: dict) -> int:
    """How many configs (incl. the q18_streamed pair) are still missing
    or errored — the SINGLE definition consumed by both this script's
    completeness check and the watchdog's progress measure."""
    missing = 0
    for metric, tag, _fn in CONFIGS:
        if metric not in extra or f"{tag}_error" in extra:
            missing += 1
    if "q18_streamed" not in extra or "q18_streamed_error" in extra:
        missing += 1
    return missing


def main():
    """Delegates to the hardened driver (scripts/q18_tpu_recapture.py):
    this module keeps the capture functions + patch/missing_count as
    the shared library, but there must be ONE recapture loop — the old
    un-hardened loop here treated a single transient tunnel hiccup as
    fatal for the rest of the run, exactly what the retry/backoff
    driver fixes. Kept as an entry point so operator muscle memory and
    the watchdog both land on the hardened behavior."""
    import q18_tpu_recapture

    q18_tpu_recapture.main()


if __name__ == "__main__":
    main()
