#!/usr/bin/env python
"""SF10 scale rehearsal (VERDICT r4 task #4; SURVEY.md:315 hard-part 6
at design scale): generate TPC-H orders+lineitem at SF10 with the
native C++ generator (~60M lineitem rows, ~7.7 GB of columns), run Q18
resident and then under a memory budget of lineitem/4, and record
times + engagement + result equality into SF10_REHEARSAL.json.

No sqlite oracle at this scale (mirroring 60M rows through Python
objects would dominate the rehearsal); correctness = the budgeted run
must produce byte-identical rows to the resident run, whose plan shape
is itself oracle-checked at every smaller SF by the test suite."""

import json
import os
import resource
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SF = float(os.environ.get("REHEARSAL_SF", "10"))


def rss_gb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def main():
    import jax

    if os.environ.get("REHEARSAL_PLATFORM", "cpu") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import tidb_tpu  # noqa: F401
    from tidb_tpu.parallel import make_mesh
    from tidb_tpu.parallel.partition import table_bytes
    from tidb_tpu.session import Session
    from tidb_tpu.storage.tpch import load_tpch
    from tidb_tpu.storage.tpch_queries import Q
    from tidb_tpu.utils.metrics import EXTERNAL_AGG, FRAGMENT_DISPATCH

    out = {"sf": SF}
    t0 = time.time()
    mesh = make_mesh()
    s = Session(chunk_capacity=1 << 20, mesh=mesh)
    counts = load_tpch(s.catalog, sf=SF)
    out["gen_s"] = round(time.time() - t0, 1)
    out["lineitem_rows"] = counts["lineitem"]
    li = s.catalog.table("test", "lineitem")
    out["lineitem_gb"] = round(table_bytes(li) / 1e9, 2)
    out["rss_after_gen_gb"] = round(rss_gb(), 1)
    print(f"# generated sf={SF}: {counts['lineitem']} lineitem rows, "
          f"{out['lineitem_gb']} GB, {out['gen_s']}s", flush=True)

    sql, _lite = Q["q18"]
    t0 = time.time()
    resident = s.query(sql)
    out["q18_resident_warm_s"] = round(time.time() - t0, 1)
    t0 = time.time()
    resident = s.query(sql)
    out["q18_resident_s"] = round(time.time() - t0, 1)
    out["q18_resident_rows_per_sec"] = round(
        counts["lineitem"] / out["q18_resident_s"], 1)
    print(f"# resident: {out['q18_resident_s']}s", flush=True)

    budget = max(1 << 20, table_bytes(li) // 4)
    out["budget_gb"] = round(budget / 1e9, 2)
    s.execute(f"SET tidb_device_cache_bytes = {budget}")
    s.execute(f"SET tidb_mem_quota_query = {budget}")
    s.execute("SET tidb_enable_tmp_storage_on_oom = 1")

    def engagements():
        return (FRAGMENT_DISPATCH.value(kind="general_segment_stream")
                + FRAGMENT_DISPATCH.value(kind="general_generic_stream")
                + EXTERNAL_AGG.value())

    e0 = engagements()
    t0 = time.time()
    streamed = s.query(sql)
    out["q18_streamed_warm_s"] = round(time.time() - t0, 1)
    t0 = time.time()
    streamed = s.query(sql)
    out["q18_streamed_s"] = round(time.time() - t0, 1)
    out["q18_streamed_rows_per_sec"] = round(
        counts["lineitem"] / out["q18_streamed_s"], 1)
    out["engaged"] = engagements() > e0
    out["overhead_vs_resident"] = round(
        out["q18_streamed_s"] / out["q18_resident_s"], 3)
    out["identical_to_resident"] = streamed == resident
    out["rss_peak_gb"] = round(rss_gb(), 1)
    print(f"# streamed: {out['q18_streamed_s']}s engaged={out['engaged']} "
          f"identical={out['identical_to_resident']}", flush=True)

    with open(os.path.join(REPO, "SF10_REHEARSAL.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
