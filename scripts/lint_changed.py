#!/usr/bin/env python
"""Git-aware diff lint: feed the working diff into the analyzer's
incremental mode (ISSUE 14 satellite).

Collects changed files from ``git diff --name-status`` (plus untracked
files from ``git status --porcelain``), keeps the ``tidb_tpu/*.py``
subset that still exists on disk — deletions are dropped (nothing to
lint), renames lint their NEW path — and hands the list to
``check_invariants.py --changed``, the sub-second AST-pass subset.

Usage: python scripts/lint_changed.py [--base REF] [--root DIR]
       [extra check_invariants args...]

``--base`` defaults to HEAD (the uncommitted working diff). A run with
no changed tidb_tpu files exits 0 and says so — an empty diff is clean
by definition, not an error.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def parse_name_status(out: str) -> list:
    """``git diff --name-status -z`` records -> candidate repo-relative
    paths. Deleted files contribute nothing (there is no file to lint);
    renames/copies (R*/C* carry TWO paths) contribute the NEW path."""
    fields = [f for f in out.split("\0") if f]
    paths = []
    i = 0
    while i < len(fields):
        status = fields[i]
        if status.startswith(("R", "C")):
            # old path, new path — lint the NEW one
            if i + 2 >= len(fields):
                break
            paths.append(fields[i + 2])
            i += 3
        elif status.startswith("D"):
            i += 2  # deleted: nothing on disk to lint
        else:
            if i + 1 >= len(fields):
                break
            paths.append(fields[i + 1])
            i += 2
    return paths


def filter_lintable(paths, root: str) -> list:
    """The analyzer's jurisdiction: tidb_tpu/*.py files that exist on
    disk (a path deleted since the diff was taken has nothing to
    lint)."""
    out = []
    seen = set()
    for p in paths:
        norm = p.replace("\\", "/")
        if not norm.endswith(".py") or not norm.startswith("tidb_tpu/"):
            continue
        if norm in seen:
            continue
        seen.add(norm)
        if os.path.exists(os.path.join(root, norm)):
            out.append(norm)
    return sorted(out)


def changed_paths(root: str, base: str) -> list:
    """Changed files vs ``base`` plus untracked files (a brand-new
    module must lint before its first commit, not after)."""
    diff = subprocess.run(
        ["git", "diff", "--name-status", "-z", base],
        capture_output=True, text=True, cwd=root, check=True)
    paths = parse_name_status(diff.stdout)
    status = subprocess.run(
        ["git", "status", "--porcelain", "-z", "--untracked-files=all"],
        capture_output=True, text=True, cwd=root, check=True)
    for rec in status.stdout.split("\0"):
        if rec.startswith("??"):
            paths.append(rec[3:])
    return paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--base", default="HEAD",
                    help="git ref to diff against (default: HEAD, the "
                         "uncommitted working diff)")
    ap.add_argument("--root", default=ROOT)
    args, passthrough = ap.parse_known_args(argv)

    try:
        paths = changed_paths(args.root, args.base)
    except (subprocess.CalledProcessError, OSError) as e:
        print(f"lint_changed: git diff failed: {e}")
        return 2
    lintable = filter_lintable(paths, args.root)
    if not lintable:
        print("lint_changed: no changed tidb_tpu/*.py files "
              f"vs {args.base} — nothing to lint")
        return 0
    print("lint_changed: " + " ".join(lintable))
    sys.path.insert(0, os.path.join(args.root, "scripts"))
    try:
        import importlib.util as _ilu

        spec = _ilu.spec_from_file_location(
            "check_invariants",
            os.path.join(args.root, "scripts", "check_invariants.py"))
        ci = _ilu.module_from_spec(spec)
        spec.loader.exec_module(ci)
    finally:
        sys.path.pop(0)
    return ci.main(["--changed", *lintable, *passthrough])


if __name__ == "__main__":
    sys.exit(main())
