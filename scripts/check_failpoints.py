#!/usr/bin/env python
"""Failpoint coverage checker (tier-1; see tests/test_failpoint_coverage.py).

Cross-references the two halves of the fault-injection surface:

  * injection SITES — `inject("name")` calls inside tidb_tpu/
  * ARMED names    — `failpoint("name", ...)` / `enable("name", ...)`
                     in tests/ (and anywhere else under the repo root)

A name armed by a test with no matching inject() site is a DEAD
failpoint: the test believes it is exercising a fault path that cannot
fire (usually a refactor moved or renamed the call site). That is an
error — exit 1.

An inject() site no test ever arms is an UNCOVERED injection point: the
fault boundary exists but nothing drives it. Listed on stdout; fails
only under --strict (the chaos suite keeps DCN points covered, but a
freshly added boundary shouldn't break CI before its test lands).

Usage: python scripts/check_failpoints.py [--strict] [--root DIR]
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Dict, List, Set, Tuple

# inject("...") — the call-site half. Matches only string literals: a
# dynamically computed name can't be statically checked and must not
# silently pass, so we also flag non-literal inject() calls.
_SITE_RE = re.compile(r"""\binject\(\s*(['"])([^'"]+)\1\s*\)""")
_SITE_DYN_RE = re.compile(r"""\binject\(\s*[^'")]""")
# failpoint("...")/enable("...") — the arming half (context manager or
# module function, with or without the `fp.` prefix)
_ARM_RE = re.compile(r"""\b(?:failpoint|enable)\(\s*(['"])([^'"]+)\1""")

_SELF = {"failpoint.py", "check_failpoints.py"}


def _py_files(root: str, subdir: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(os.path.join(root, subdir)):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        out.extend(os.path.join(dirpath, f) for f in filenames
                   if f.endswith(".py") and f not in _SELF)
    return sorted(out)


def scan(root: str) -> Tuple[Dict[str, List[str]], Dict[str, List[str]],
                             List[str]]:
    """-> (sites, armed, dynamic_sites): name -> ["file:line", ...].

    A site also counts as ARMED (covered) when its exact name appears
    as a string literal anywhere under tests/ — chaos grids arm
    failpoints through parametrized lists (`failpoint(fault, ...)`), so
    requiring the literal inside the failpoint() call itself would
    misreport every grid as uncovered. The DEAD direction stays strict:
    only names inside literal failpoint()/enable() calls can be dead."""
    sites: Dict[str, List[str]] = {}
    armed: Dict[str, List[str]] = {}
    dynamic: List[str] = []
    for path in _py_files(root, "tidb_tpu"):
        rel = os.path.relpath(path, root)
        with open(path, encoding="utf-8") as f:
            for ln, line in enumerate(f, 1):
                for m in _SITE_RE.finditer(line):
                    sites.setdefault(m.group(2), []).append(f"{rel}:{ln}")
                if _SITE_DYN_RE.search(line) and "def inject" not in line:
                    dynamic.append(f"{rel}:{ln}")
    test_blobs: List[Tuple[str, str]] = []
    for sub in ("tests", "tidb_tpu", "scripts"):
        for path in _py_files(root, sub):
            rel = os.path.relpath(path, root)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            if sub == "tests":
                test_blobs.append((rel, text))
            for ln, line in enumerate(text.splitlines(), 1):
                for m in _ARM_RE.finditer(line):
                    armed.setdefault(m.group(2), []).append(f"{rel}:{ln}")
    for name in sites:
        if name in armed:
            continue
        for rel, text in test_blobs:
            if f'"{name}"' in text or f"'{name}'" in text:
                armed.setdefault(name, []).append(f"{rel} (mention)")
                break
    return sites, armed, dynamic


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--strict", action="store_true",
                    help="also fail on uncovered injection points")
    ap.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    args = ap.parse_args(argv)

    sites, armed, dynamic = scan(args.root)
    dead: Set[str] = set(armed) - set(sites)
    uncovered: Set[str] = set(sites) - set(armed)

    rc = 0
    if dead:
        rc = 1
        print(f"DEAD failpoints ({len(dead)}): armed by a test but no "
              "inject() call site exists —")
        for name in sorted(dead):
            for loc in armed[name]:
                print(f"  {name}  armed at {loc}")
    if dynamic:
        rc = 1
        print(f"NON-LITERAL inject() calls ({len(dynamic)}): cannot be "
              "statically checked —")
        for loc in dynamic:
            print(f"  {loc}")
    if uncovered:
        print(f"uncovered injection points ({len(uncovered)}): no test "
              "arms them —")
        for name in sorted(uncovered):
            print(f"  {name}  at {', '.join(sites[name])}")
        if args.strict:
            rc = 1
    if rc == 0:
        print(f"failpoints ok: {len(sites)} sites, "
              f"{len(set(armed) & set(sites))} covered, "
              f"{len(uncovered)} uncovered")
    return rc


if __name__ == "__main__":
    sys.exit(main())
