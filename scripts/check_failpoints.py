#!/usr/bin/env python
"""Failpoint coverage checker (tier-1; see tests/test_failpoint_coverage.py).

Thin CLI shim: the scan lives in ``tidb_tpu.analysis.registry`` (the
``failpoint-coverage`` pass of ``scripts/check_invariants.py``).  The
original surface (``scan``/``main``) is preserved.

Cross-references the two halves of the fault-injection surface:

  * injection SITES — `inject("name")` calls inside tidb_tpu/
  * ARMED names    — `failpoint("name", ...)` / `enable("name", ...)`

A name armed by a test with no matching inject() site is a DEAD
failpoint — exit 1.  An inject() site no test ever arms is UNCOVERED:
listed on stdout; fails only under --strict.

Usage: python scripts/check_failpoints.py [--strict] [--root DIR]
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Set

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
try:
    # keep this checker jax-free: stub the tidb_tpu namespace so the
    # analysis import never executes the engine __init__ (which
    # imports jax). No-op under pytest.
    from _light_import import ensure_light_tidb_tpu  # noqa: E402
finally:
    sys.path.pop(0)
ensure_light_tidb_tpu(_ROOT)

from tidb_tpu.analysis.registry import failpoint_scan  # noqa: E402


def scan(root: str):
    """Back-compat: -> (sites, armed, dynamic): name -> ["file:line"]."""
    return failpoint_scan(root)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--strict", action="store_true",
                    help="also fail on uncovered injection points")
    ap.add_argument("--root", default=_ROOT)
    args = ap.parse_args(argv)

    sites, armed, dynamic = scan(args.root)
    dead: Set[str] = set(armed) - set(sites)
    uncovered: Set[str] = set(sites) - set(armed)

    rc = 0
    if dead:
        rc = 1
        print(f"DEAD failpoints ({len(dead)}): armed by a test but no "
              "inject() call site exists —")
        for name in sorted(dead):
            for loc in armed[name]:
                print(f"  {name}  armed at {loc}")
    if dynamic:
        rc = 1
        print(f"NON-LITERAL inject() calls ({len(dynamic)}): cannot be "
              "statically checked —")
        for loc in dynamic:
            print(f"  {loc}")
    if uncovered:
        print(f"uncovered injection points ({len(uncovered)}): no test "
              "arms them —")
        for name in sorted(uncovered):
            print(f"  {name}  at {', '.join(sites[name])}")
        if args.strict:
            rc = 1
    if rc == 0:
        print(f"failpoints ok: {len(sites)} sites, "
              f"{len(set(armed) & set(sites))} covered, "
              f"{len(uncovered)} uncovered")
    return rc


if __name__ == "__main__":
    sys.exit(main())
