#!/usr/bin/env python
"""Regenerate the committed DCN wire-protocol artifacts from the static
protocol model (tidb_tpu/analysis/wire_protocol.py):

  tidb_tpu/analysis/wire_protocol.json   machine-readable model — the
                                         runtime wire witness
                                         (analysis/sanitizer.py) diffs
                                         real traffic against it
  docs/WIRE_PROTOCOL.md                  the generated reference table
                                         (cmd -> sender sites ->
                                         handler -> fields)

The protocol-conformance pass (and a tier-1 drift test) assert both
files match a fresh extraction, so protocol edits that skip this script
fail the analyzer — the model can never silently rot.

Usage: python scripts/gen_wire_protocol.py [--root DIR] [--check]

``--check`` writes nothing and exits 1 when either artifact is stale.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _import_analysis(root: str):
    sys.path.insert(0, root)
    try:
        import importlib.util as _ilu
        _spec = _ilu.spec_from_file_location(
            "_light_import",
            os.path.join(root, "scripts", "_light_import.py"))
        _light = _ilu.module_from_spec(_spec)
        _spec.loader.exec_module(_light)
        _light.ensure_light_tidb_tpu(root)
        from tidb_tpu.analysis import wire_protocol
        from tidb_tpu.analysis.core import Project
    finally:
        sys.path.pop(0)
    return wire_protocol, Project


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=ROOT)
    ap.add_argument("--check", action="store_true",
                    help="verify the committed artifacts are fresh "
                         "(exit 1 on drift), write nothing")
    args = ap.parse_args(argv)

    wp, Project = _import_analysis(ROOT)
    project = Project(args.root)
    wire = wp.to_wire_model(wp.extract_model(project))
    json_text = json.dumps(wire, indent=2, sort_keys=True) + "\n"
    md_text = wp.render_markdown(wire)

    json_path = os.path.join(args.root, wp.MODEL_REL_PATH)
    md_path = os.path.join(args.root, wp.DOC_REL_PATH)
    targets = [(json_path, json_text), (md_path, md_text)]
    if args.check:
        stale = []
        for path, want in targets:
            try:
                with open(path, encoding="utf-8") as f:
                    have = f.read()
            except OSError:
                have = None
            if have != want:
                stale.append(os.path.relpath(path, args.root))
        if stale:
            print("stale wire-protocol artifacts: " + ", ".join(stale)
                  + " (run scripts/gen_wire_protocol.py)")
            return 1
        print("wire-protocol artifacts are fresh")
        return 0
    for path, text in targets:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {os.path.relpath(path, args.root)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
