#!/usr/bin/env python
"""On-chip recapture of EVERY config the tunnel has denied so far —
Q18 (+streamed), SSB Q3.2, TPC-DS Q95 — with retry + backoff on the
transient transport errors that killed them in BENCH_tpu.json.

History: the round-4 captures lost these configs to mid-run tunnel
deaths (`remote_compile: Unexpected EOF`, `UNAVAILABLE`) — remote
compiles through the HTTP tunnel take minutes per program and the
backend drops. The first version of this script retook ONLY Q18 and
gave up on the first error; scripts/missing_configs_recapture.py then
generalized it to every missing config but still treated one transient
hiccup as fatal for the rest of the run. This hardened driver (ISSUE
10) reuses those capture functions and adds the missing piece: an
error that MATCHES the known-transient transport signatures is retried
in place with exponential backoff (the tunnel usually comes back
within a minute or two), while a non-transient failure records its
error and moves on. Every successful config patches into
BENCH_tpu.json immediately, in place of its error entry, so a later
death never loses earlier results.

Run solo (acquires the chip lock via bench.chip_lock). Env knobs:
RECAPTURE_ATTEMPTS (default 3), RECAPTURE_BACKOFF_S (default 45,
doubles per retry).
"""

import gc
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402
from missing_configs_recapture import (  # noqa: E402
    CONFIGS,
    missing_count,
    patch,
)

ATTEMPTS = max(1, int(os.environ.get("RECAPTURE_ATTEMPTS", "3")))
BACKOFF_S = float(os.environ.get("RECAPTURE_BACKOFF_S", "45"))

# the transport-failure signatures observed across BENCH_tpu rounds:
# tunnel EOFs mid-remote-compile, gRPC UNAVAILABLE/DEADLINE flaps, and
# plain socket drops. Anything else (OOM, a real engine error, an
# oracle mismatch raised as an exception) is NOT retried — re-running
# would burn the chip window on a deterministic failure.
TRANSIENT_SIGNATURES = (
    "UNAVAILABLE",
    "remote_compile",
    "Unexpected EOF",
    "DEADLINE_EXCEEDED",
    "Connection reset",
    "Connection refused",
    "Broken pipe",
    "Socket closed",
    "RPC failed",
    "tunnel",
)


def is_transient(err: str) -> bool:
    return any(sig.lower() in err.lower() for sig in TRANSIENT_SIGNATURES)


def capture_with_retry(tag, fn, mesh):
    """Run one config's capture, retrying transient transport errors
    with exponential backoff. Returns the `out` dict to patch (carries
    either the metrics or the final `<tag>_error`).

    Two error surfaces are classified: exceptions raised by the capture
    fn, AND `*_error` entries the fn recorded internally instead of
    raising (capture_q18 swallows its q18_streamed half's failure so a
    streamed hiccup can't lose the main config) — a transient error on
    EITHER surface re-runs the whole config."""
    backoff = BACKOFF_S

    def retry_or_give_up(out, err, attempt):
        """-> None to retry, else the final (out, False)."""
        if not is_transient(err):
            print(f"{tag}: non-transient failure, not retrying: {err}",
                  flush=True)
            return out, False
        if attempt == ATTEMPTS:
            print(f"{tag}: still transient after {ATTEMPTS} attempts: "
                  f"{err}", flush=True)
            return out, False
        nonlocal backoff
        print(f"{tag}: transient ({err}); retry {attempt + 1}/"
              f"{ATTEMPTS} in {backoff:.0f}s", flush=True)
        gc.collect()
        time.sleep(backoff)
        backoff *= 2
        return None

    final = None
    for attempt in range(1, ATTEMPTS + 1):
        out = {f"{tag}_recapture_ts": time.strftime("%Y-%m-%d %H:%M:%S"),
               f"{tag}_load_before": bench.machine_load()}
        try:
            fn(mesh, out)
            out[f"{tag}_load_after"] = bench.machine_load()
            if attempt > 1:
                out[f"{tag}_recapture_attempts"] = attempt
            # the fn may have recorded a swallowed sub-config error
            # (q18_streamed) instead of raising: transient ones retry
            # the whole config like an exception would have
            recorded = [v for k, v in out.items() if k.endswith("_error")]
            if not recorded:
                return out, True
            final = retry_or_give_up(out, str(recorded[0]), attempt)
        except Exception as e:  # noqa: BLE001 — classified right below
            err = f"{type(e).__name__}: {e}"[:300]
            out[f"{tag}_error"] = err
            out[f"{tag}_load_after"] = bench.machine_load()
            final = retry_or_give_up(out, err, attempt)
            if final is not None and attempt == ATTEMPTS \
                    and is_transient(err):
                out[f"{tag}_error"] = (
                    f"transient after {ATTEMPTS} attempts: {err}"[:300])
        if final is not None:
            return final
    return out, False  # unreachable (ATTEMPTS >= 1), belt-and-braces


def main():
    lock = bench.chip_lock()
    if lock[0] == "unavailable":
        # never start a TPU client while a live process holds the chip
        # (overlapping clients wedge the tunnel — BASELINE.md r2)
        print(f"chip lock {lock[1]}; aborting on-chip recapture")
        bench.chip_unlock(lock[0])
        sys.exit(3)
    ok = True
    try:
        import jax

        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        from tidb_tpu.parallel import make_mesh

        mesh = make_mesh()
        path = os.path.join(REPO, "BENCH_tpu.json")
        for metric, tag, fn in CONFIGS:
            have = json.load(open(path))["extra"]
            done = metric in have and f"{tag}_error" not in have
            if tag == "q18":  # q18 is complete only WITH its streamed pair
                done = done and "q18_streamed" in have \
                    and "q18_streamed_error" not in have
            if done:
                print(f"{tag}: already captured; skipping", flush=True)
                continue
            out, captured = capture_with_retry(tag, fn, mesh)
            patch(out)  # each success lands immediately, error entries
            # are replaced in place (stale *_error keys stripped by
            # patch's recaptured-marker scan)
            gc.collect()
            if not captured:
                ok = False
                if is_transient(out.get(f"{tag}_error", "")):
                    # the tunnel outlived every backoff window: later
                    # configs would pay the same dead transport — stop
                    # and let the watchdog re-probe the chip
                    break
        have = json.load(open(path))["extra"]
        if missing_count(have):
            ok = False
    finally:
        bench.chip_unlock(lock[0])
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
