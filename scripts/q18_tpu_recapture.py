#!/usr/bin/env python
"""Focused on-chip recapture of the Q18 config (+ streamed mode).

The full watchdog capture lost exactly one config to a transient tunnel
error (`remote_compile: Unexpected EOF`); this retakes Q18 under the
same protocol — chip lock held, load snapshots, sqlite oracle — and
patches the result into BENCH_tpu.json in place of the error."""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def main():
    lock = bench.chip_lock()
    if lock[0] == "unavailable":
        # never start a TPU client while a live process holds the chip
        # (overlapping clients wedge the tunnel — BASELINE.md r2)
        print(f"chip lock {lock[1]}; aborting on-chip recapture")
        bench.chip_unlock(lock[0])
        sys.exit(3)
    try:
        extra = {}
        extra["recapture_load_before"] = bench.machine_load()
        import tidb_tpu  # noqa: F401
        from tidb_tpu.parallel import make_mesh
        from tidb_tpu.session import Session
        from tidb_tpu.storage.tpch import load_tpch
        from tidb_tpu.storage.tpch_queries import Q
        from tidb_tpu.testutil import mirror_to_sqlite

        sf = float(os.environ.get("BENCH_SF_Q18", "0.2"))
        mesh = make_mesh()
        s = Session(chunk_capacity=1 << 20, mesh=mesh)
        counts = load_tpch(s.catalog, sf=sf)
        conn = mirror_to_sqlite(
            s.catalog, tables=["lineitem", "orders", "customer"])
        sql, lite = Q["q18"]
        t0 = time.time()
        rps, vs, best, check = bench.bench_query(
            s, sql, conn, lite or sql, counts["lineitem"],
            reps=int(os.environ.get("BENCH_REPS", "2")),
            extra=extra, tag="q18")
        print(f"q18: {rps:.1f} rows/s, {vs:.3f}x sqlite, check={check}, "
              f"wall={time.time() - t0:.0f}s", flush=True)

        # streamed mode on the real chip (same logic as bench.py)
        from tidb_tpu.parallel.partition import table_bytes
        from tidb_tpu.utils.metrics import FRAGMENT_DISPATCH

        def sd():
            return (FRAGMENT_DISPATCH.value(kind="general_segment_stream")
                    + FRAGMENT_DISPATCH.value(kind="general_generic_stream"))

        li = s.catalog.table("test", "lineitem")
        li_bytes = table_bytes(li)
        budget = max(1 << 20, li_bytes // 4)
        best_res = best
        s.execute(f"SET tidb_device_cache_bytes = {budget}")
        d0 = sd()
        rps_s, vs_s, best_s, check_s = bench.bench_query(
            s, sql, conn, lite or sql, counts["lineitem"],
            reps=int(os.environ.get("BENCH_REPS", "2")),
            extra=extra, tag="q18_streamed")
        engaged = sd() > d0
        if not engaged:
            # mirror bench.py: auto routing bypassed the fragment tier,
            # so force the device engine for a true streamed/resident
            # pair instead of recording a meaningless ratio
            print("q18 streamed: forcing device engine for a true pair",
                  flush=True)
            s.execute("SET tidb_device_engine_mode = 'force'")
            s.execute("SET tidb_device_cache_bytes = 8589934592")
            _, _, best_res, _ = bench.bench_query(
                s, sql, conn, lite or sql, counts["lineitem"],
                reps=int(os.environ.get("BENCH_REPS", "2")))
            s.execute(f"SET tidb_device_cache_bytes = {budget}")
            d0 = sd()
            rps_s, vs_s, best_s, check_s = bench.bench_query(
                s, sql, conn, lite or sql, counts["lineitem"],
                reps=int(os.environ.get("BENCH_REPS", "2")),
                extra=extra, tag="q18_streamed")
            engaged = sd() > d0
            s.execute("SET tidb_device_engine_mode = 'auto'")
        streamed = {
            "rows_per_sec": round(rps_s, 1), "vs_sqlite": round(vs_s, 3),
            "budget_bytes": budget, "lineitem_bytes": li_bytes,
            "engaged": bool(engaged),
            "overhead_vs_resident": round(best_s / best_res, 3),
            "check": check_s,
        }
        print(f"q18_streamed: {streamed}", flush=True)
        extra["recapture_load_after"] = bench.machine_load()

        path = os.path.join(REPO, "BENCH_tpu.json")
        art = json.load(open(path))
        art["extra"].pop("q18_error", None)
        art["extra"].pop("q18_streamed_error", None)
        art["extra"]["tpch_q18_rows_per_sec"] = round(rps, 1)
        art["extra"]["q18_vs_sqlite"] = round(vs, 3)
        art["extra"]["q18_sf"] = sf
        art["extra"]["q18_recaptured"] = (
            "transient tunnel error in the first pass; retaken solo "
            "under the chip lock")
        art["extra"]["q18_streamed"] = streamed
        for k, v in extra.items():
            art["extra"][k] = v
        if "MISMATCH" in check:
            art["extra"]["q18_check"] = check
        tmp = path + ".patch"
        json.dump(art, open(tmp, "w"))
        os.replace(tmp, path)
        print("BENCH_tpu.json patched", flush=True)
    finally:
        bench.chip_unlock(lock[0])


if __name__ == "__main__":
    main()
