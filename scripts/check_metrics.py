#!/usr/bin/env python
"""Metrics hygiene checker (tier-1; see tests/test_metrics_coverage.py).

Thin CLI shim: the logic lives in ``tidb_tpu.analysis.registry`` (the
``metrics-coverage`` pass of ``scripts/check_invariants.py``) so the
invariant driver and this entry point can never drift.  The original
surface (``collect``/``check``/``main``) is preserved for the tests and
for muscle memory.

Every metric registered by importing ``tidb_tpu.utils.metrics`` must:

  * render in ``render_prometheus()`` output
  * carry a non-empty help string
  * be mentioned by name in README.md

Duplicate metric names are an error too.

Usage: python scripts/check_metrics.py [--root DIR] [--readme FILE]
"""

from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
try:
    # keep this checker jax-free: stub the tidb_tpu namespace so the
    # analysis import (and the stdlib-only utils.metrics import inside
    # it) never executes the engine __init__. No-op under pytest.
    from _light_import import ensure_light_tidb_tpu  # noqa: E402
finally:
    sys.path.pop(0)
ensure_light_tidb_tpu(_ROOT)

from tidb_tpu.analysis.registry import (  # noqa: E402
    metrics_collect,
    metrics_problems,
)


def collect(root: str):
    """Back-compat: -> (metrics module, registered collectors)."""
    return metrics_collect(root)


def check(root: str, readme_path: str):
    """Back-compat: -> (problems: list[str], metric_names: list[str])."""
    return metrics_problems(root, readme_path)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=_ROOT)
    ap.add_argument("--readme", default=None,
                    help="README to scan (default: <root>/README.md)")
    args = ap.parse_args(argv)
    readme = args.readme or os.path.join(args.root, "README.md")

    try:
        problems, names = check(args.root, readme)
    except RuntimeError as e:
        # wrong-checkout refusal from metrics_collect (tidb_tpu already
        # imported from a different root) — report, don't traceback
        print(f"metrics check FAILED: {e}")
        return 1
    if problems:
        print(f"metrics check FAILED ({len(problems)} problems):")
        for p in problems:
            print(f"  {p}")
        return 1
    print(f"metrics ok: {len(names)} collectors rendered, documented, "
          "and help-stringed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
