#!/usr/bin/env python
"""Metrics hygiene checker (tier-1; see tests/test_metrics_coverage.py).

Every metric registered by importing ``tidb_tpu.utils.metrics`` must:

  * render in ``render_prometheus()`` output (HELP/TYPE lines — a
    collector registered to a private registry would silently vanish
    from /metrics)
  * carry a non-empty help string (Prometheus consumers and the README
    table both read it)
  * be mentioned by name in README.md (an operator discovering a metric
    on /metrics must find prose for it; an undocumented metric is an
    orphan)

Duplicate metric names are an error too (the second collector's samples
shadow or interleave with the first's in the exposition).

Usage: python scripts/check_metrics.py [--root DIR] [--readme FILE]
"""

from __future__ import annotations

import argparse
import os
import sys


def collect(root: str):
    """Import the metrics module from `root` and return its registered
    collectors. Import is side-effect-free beyond registration."""
    sys.path.insert(0, root)
    try:
        import importlib

        mod = importlib.import_module("tidb_tpu.utils.metrics")
    finally:
        sys.path.pop(0)
    with mod.REGISTRY.lock:
        metrics = list(mod.REGISTRY.metrics)
    return mod, metrics


def check(root: str, readme_path: str):
    """-> (problems: list[str], metric_names: list[str])."""
    mod, metrics = collect(root)
    rendered = mod.render_prometheus()
    try:
        with open(readme_path, encoding="utf-8") as f:
            readme = f.read()
    except OSError as e:
        return [f"README unreadable: {e}"], []

    problems = []
    seen = {}
    for m in metrics:
        if m.name in seen:
            problems.append(
                f"DUPLICATE metric name {m.name!r} (registered twice)")
        seen[m.name] = m
        if not (m.help or "").strip():
            problems.append(f"metric {m.name!r} has no help string")
        if f"# HELP {m.name} " not in rendered:
            problems.append(
                f"metric {m.name!r} missing from render_prometheus() output")
        if m.name not in readme:
            problems.append(
                f"ORPHAN metric {m.name!r}: not mentioned in README.md")
    return problems, sorted(seen)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--readme", default=None,
                    help="README to scan (default: <root>/README.md)")
    args = ap.parse_args(argv)
    readme = args.readme or os.path.join(args.root, "README.md")

    problems, names = check(args.root, readme)
    if problems:
        print(f"metrics check FAILED ({len(problems)} problems):")
        for p in problems:
            print(f"  {p}")
        return 1
    print(f"metrics ok: {len(names)} collectors rendered, documented, "
          "and help-stringed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
