#!/usr/bin/env python
"""Engine invariant analyzer CLI (tier-1; see tests/test_static_analysis.py).

Runs the AST lint passes in tidb_tpu/analysis/ over the repo:

  jit-hygiene          device programs module-level + argument-driven
  host-sync            no silent device→host syncs in hot loop bodies
  lock-discipline      lock-order cycles, mixed locked/unlocked writes
  resource-lifecycle   acquires (pins/charges/cursors/arms) reach their
                       release on every path
  blocking-under-lock  no registered lock held across a blocking call
  protocol-conformance DCN wire protocol: senders/handler arms agree on
                       cmds+fields, worker re-sends carry the envelope,
                       committed model (wire_protocol.json) is fresh
  cache-key-completeness every value a cached_jit/get_fragment traced
                       body closes over is named in its cache key
  metrics-coverage     /metrics collectors rendered + documented
  failpoint-coverage   no dead/armed-but-siteless failpoints
  sysvar-coverage      tidb_* sysvars registered, read, documented
  error-shape          no bare/swallowing excepts; errors carry codes

Exit 0 only with zero unsuppressed violations.  Suppressions need an
inline reason (`# lint: disable=<pass> -- <reason>`, or
`# host-sync: <reason>` / `# lifecycle: <reason>` for intentional
syncs/handoffs) and are counted in the report so the allowlist stays
visible.

``--json`` emits the machine-readable report (violations, suppressions,
per-pass timings; schema asserted tier-1). ``--changed <paths...>``
restricts the AST passes to the given repo-relative files — the
incremental mode for the builder loop, well under a second on a diff
(the registry passes need the whole tree and are skipped there unless
explicitly selected with --pass).

Usage: python scripts/check_invariants.py [--root DIR] [--pass NAME]
       [--list] [--syncs] [--json] [--changed PATH...]
"""

from __future__ import annotations

import argparse
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _import_analysis(root: str):
    sys.path.insert(0, root)
    try:
        import importlib.util as _ilu
        _spec = _ilu.spec_from_file_location(
            "_light_import",
            os.path.join(root, "scripts", "_light_import.py"))
        _light = _ilu.module_from_spec(_spec)
        _spec.loader.exec_module(_light)
        # keep the analyzer jax-free: register a namespace stub for
        # tidb_tpu so importing the analysis subpackage never executes
        # the engine __init__ (which imports jax). No-op under pytest.
        _light.ensure_light_tidb_tpu(root)
        from tidb_tpu.analysis import core  # noqa: F401
        from tidb_tpu import analysis
    finally:
        sys.path.pop(0)
    return analysis


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=ROOT)
    ap.add_argument("--pass", dest="passes", action="append", default=None,
                    metavar="NAME", help="run only the named pass(es)")
    ap.add_argument("--list", action="store_true",
                    help="list available passes and exit")
    ap.add_argument("--syncs", action="store_true",
                    help="also print the annotated intentional host-sync "
                         "table (the README source of truth)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report (violations, "
                         "suppressions, per-pass timings) as JSON")
    ap.add_argument("--changed", nargs="+", default=None, metavar="PATH",
                    help="incremental mode: lint only these repo-relative "
                         "files with the AST passes (<1s on a diff)")
    args = ap.parse_args(argv)

    analysis = _import_analysis(ROOT)
    passes = analysis.all_passes()
    if args.list:
        for p in passes:
            print(f"{p.id:20s} {p.doc}")
        return 0
    if args.passes:
        known = {p.id for p in passes}
        unknown = [n for n in args.passes if n not in known]
        if unknown:
            print(f"unknown pass(es): {', '.join(unknown)} "
                  f"(have: {', '.join(sorted(known))})")
            return 2
        passes = [p for p in passes if p.id in args.passes]
    if args.changed is not None and not args.passes:
        # a changed subset cannot prove registry coverage either way:
        # run only the file-scoped AST passes over the diff
        from tidb_tpu.analysis.core import AST_PASS_IDS

        passes = [p for p in passes if p.id in AST_PASS_IDS]

    driver = analysis.Driver(args.root, passes, changed=args.changed)
    reports = driver.run()
    if args.json:
        import json

        print(json.dumps(driver.to_json(reports), indent=2,
                         sort_keys=True))
        return 0 if not any(r.violations or r.problems
                            for r in reports) else 1
    text, rc = driver.render(reports)
    print(text)

    if args.syncs:
        from tidb_tpu.analysis.host_sync import annotated_sites
        from tidb_tpu.analysis.resource_lifecycle import lifecycle_sites

        print("\nannotated intentional host syncs:")
        for rel, line, reason in annotated_sites(driver.project):
            print(f"  {rel}:{line}  {reason}")
        print("\nannotated lifecycle handoffs:")
        for rel, line, reason in lifecycle_sites(driver.project):
            print(f"  {rel}:{line}  {reason}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
