#!/usr/bin/env python
"""Engine invariant analyzer CLI (tier-1; see tests/test_static_analysis.py).

Runs the AST lint passes in tidb_tpu/analysis/ over the repo:

  jit-hygiene          device programs module-level + argument-driven
  host-sync            no silent device→host syncs in hot loop bodies
  lock-discipline      lock-order cycles, mixed locked/unlocked writes
  metrics-coverage     /metrics collectors rendered + documented
  failpoint-coverage   no dead/armed-but-siteless failpoints
  sysvar-coverage      tidb_* sysvars registered, read, documented
  error-shape          no bare/swallowing excepts; errors carry codes

Exit 0 only with zero unsuppressed violations.  Suppressions need an
inline reason (`# lint: disable=<pass> -- <reason>`, or
`# host-sync: <reason>` for intentional syncs) and are counted in the
report so the allowlist stays visible.

Usage: python scripts/check_invariants.py [--root DIR] [--pass NAME]
       [--list] [--syncs]
"""

from __future__ import annotations

import argparse
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _import_analysis(root: str):
    sys.path.insert(0, root)
    try:
        import importlib.util as _ilu
        _spec = _ilu.spec_from_file_location(
            "_light_import",
            os.path.join(root, "scripts", "_light_import.py"))
        _light = _ilu.module_from_spec(_spec)
        _spec.loader.exec_module(_light)
        # keep the analyzer jax-free: register a namespace stub for
        # tidb_tpu so importing the analysis subpackage never executes
        # the engine __init__ (which imports jax). No-op under pytest.
        _light.ensure_light_tidb_tpu(root)
        from tidb_tpu.analysis import core  # noqa: F401
        from tidb_tpu import analysis
    finally:
        sys.path.pop(0)
    return analysis


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=ROOT)
    ap.add_argument("--pass", dest="passes", action="append", default=None,
                    metavar="NAME", help="run only the named pass(es)")
    ap.add_argument("--list", action="store_true",
                    help="list available passes and exit")
    ap.add_argument("--syncs", action="store_true",
                    help="also print the annotated intentional host-sync "
                         "table (the README source of truth)")
    args = ap.parse_args(argv)

    analysis = _import_analysis(ROOT)
    passes = analysis.all_passes()
    if args.list:
        for p in passes:
            print(f"{p.id:20s} {p.doc}")
        return 0
    if args.passes:
        known = {p.id for p in passes}
        unknown = [n for n in args.passes if n not in known]
        if unknown:
            print(f"unknown pass(es): {', '.join(unknown)} "
                  f"(have: {', '.join(sorted(known))})")
            return 2
        passes = [p for p in passes if p.id in args.passes]

    driver = analysis.Driver(args.root, passes)
    reports = driver.run()
    text, rc = driver.render(reports)
    print(text)

    if args.syncs:
        from tidb_tpu.analysis.host_sync import annotated_sites

        print("\nannotated intentional host syncs:")
        for rel, line, reason in annotated_sites(driver.project):
            print(f"  {rel}:{line}  {reason}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
