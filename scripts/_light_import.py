"""Import tidb_tpu.analysis without the engine's device stack.

``tidb_tpu/__init__.py`` imports jax and mutates global jax config
(x64 mode, compilation cache) as an import side effect.  The invariant
analyzer's contract is the opposite: pure AST + stdlib, a couple of
seconds end to end, runnable on a box with no jax at all.  Importing
``tidb_tpu.analysis`` (or ``tidb_tpu.utils.metrics`` — stdlib-only
itself) the normal way would execute the parent package first and
break that contract.

``ensure_light_tidb_tpu(root)`` registers a bare namespace package for
``tidb_tpu`` so submodule imports resolve against ``root`` WITHOUT
running the package ``__init__``.  It is a no-op when the real package
is already imported (pytest: the suite imports the engine first, and
the analyzer modules must be shared, not shadowed).

Only the check CLIs may call this: the stub skips the x64 flag, so a
process that later imports the engine proper would compute wrong
decimals.  Scripts are single-purpose processes; that cannot happen.
"""

from __future__ import annotations

import importlib.machinery
import importlib.util
import os
import sys


def ensure_light_tidb_tpu(root: str) -> None:
    if "tidb_tpu" in sys.modules:
        return
    spec = importlib.machinery.ModuleSpec("tidb_tpu", None, is_package=True)
    spec.submodule_search_locations = [os.path.join(root, "tidb_tpu")]
    sys.modules["tidb_tpu"] = importlib.util.module_from_spec(spec)
